"""Benchmark: PQL Intersect+Count throughput (the north-star metric).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload (BASELINE.md config 1/4 shape): a Star-Trace style index — a
device-resident row matrix of ``n_slices`` slices × ``n_rows`` rows of
packed SLICE_WIDTH-bit bitmaps — served a stream of
``Count(Intersect(Bitmap(r1), Bitmap(r2)))`` queries.  Queries run in
batches through ONE fused computation per batch via
``dispatch.gather_count`` — the strategy stack the product path uses
(the TPU-native form of the reference's per-slice goroutine fan-out +
SIMD loop, executor.go:1115-1244 + roaring/assembly_amd64.s:60-77):

- row working set tiny → the MXU all-pairs Gram strategy (one int8
  matmul of the unpacked bits computes every pair count; per-query
  answers are lookups, and XLA hoists the matmul out of the stream loop
  since it depends only on the row matrix);
- rows fit VMEM → the resident Pallas kernel (whole row set streamed
  HBM→VMEM once per chunk, queries answered from VMEM);
- otherwise → the scalar-prefetch gather Pallas kernel (two row DMAs
  per (query, slice) grid step, no materialized intermediates).

Timing methodology: all ``iters`` batches are chained inside one jitted
``lax.scan`` and the timer stops only when the results have been fetched
to host memory.  This is deliberate: the TPU here sits behind a remote
tunnel with ~70 ms round-trip latency and unreliable
``block_until_ready`` semantics, so per-batch host dispatch would
measure the tunnel, not the device, and blocking on the last output
alone under-measures.  One dispatch + explicit host fetch amortizes the
round trip across the whole query stream and cannot finish early.

vs_baseline (headline): ratio against the MEASURED compiled-loop bound
of the reference's kernel hot loop — native/refloop_bench.c compiles the
exact popcntAndSliceAsm semantics (Σ popcount(a[i] & b[i]),
roaring/assembly_amd64.s:60-77) with -mpopcnt and measures it on this
host, giving a defensible single-core reference-equivalent q/s at the
bench shape.  The single-threaded numpy ratio (the round-1..4
denominator) is kept as the secondary field ``vs_numpy``.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _ref_loop_bytes_per_s() -> float:
    """Measured bytes/s of the reference's AND+POPCNT hot loop on this host.

    Builds and runs ``native/refloop_bench.c`` (the compiled stand-in for
    roaring/assembly_amd64.s:60-77 — the Go toolchain is absent here, see
    BASELINE.md) and returns its DRAM-bound streaming rate.  The result
    is the denominator for the headline ``vs_baseline``: reference
    pair-count q/s at shape (n_slices, 2^20 cols) = rate / (2 * n_slices
    * 128 KiB).  Cached per process; ``BENCH_REF_BYTES_PER_S`` overrides;
    falls back to the value measured on the round-5 build host when the
    C toolchain is unavailable.
    """
    env = os.environ.get("BENCH_REF_BYTES_PER_S")
    if env:
        _ref_loop_bytes_per_s._measured = True  # operator-supplied
        return float(env)
    cached = getattr(_ref_loop_bytes_per_s, "_cache", None)
    if cached is not None:
        return cached
    rate = 2.38e10  # round-5 build-host measurement (fallback)
    measured = False
    try:
        import subprocess
        import tempfile

        src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "native", "refloop_bench.c")
        with tempfile.TemporaryDirectory() as td:
            exe = os.path.join(td, "refloop_bench")
            subprocess.run(["gcc", "-O2", "-mpopcnt", "-o", exe, src],
                           check=True, capture_output=True, timeout=60)
            out = subprocess.run([exe], check=True, capture_output=True,
                                 timeout=120).stdout
        rate = float(json.loads(out)["bytes_per_s"])
        measured = True
    except Exception:
        import sys

        print("bench: refloop_bench unavailable; vs_baseline uses the "
              "build-host fallback rate (ref_loop_measured=false)",
              file=sys.stderr)
    _ref_loop_bytes_per_s._cache = rate
    _ref_loop_bytes_per_s._measured = measured
    return rate


def _best_of_runs(fn, default_runs=5):
    """Min wall time over N runs (tunnel jitter; see headline config)."""
    runs = max(1, int(os.environ.get("BENCH_TIMED_RUNS", str(default_runs))))
    dt = float("inf")
    out = None
    for _ in range(runs):
        t0 = time.perf_counter()
        out = fn()
        dt = min(dt, time.perf_counter() - t0)
    return dt, out


def bench_setbit() -> dict:
    """Config 2: SetBit op/sec (the `pilosa bench --operation set-bit`
    analog, ctl/bench.go:71-102).  Reports the CONCURRENT server ingest
    shape as the headline — singleton SetBit requests from BENCH_THREADS
    clients group-committing through the write queue (executor ->
    vectorized fragment batches + one WAL append per commit) — with the
    sequential per-op-durable fragment rate in the unit string for
    apples-to-apples against the reference's single client."""
    n = int(os.environ.get("BENCH_OPS", "20000"))
    n_threads = int(os.environ.get("BENCH_THREADS", "8"))
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from pilosa_tpu.core.fragment import Fragment
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    rng = np.random.default_rng(7)
    rows = rng.integers(0, 1000, size=n)
    cols = rng.integers(0, 1 << 20, size=n)

    # (a) sequential fragment loop, per-op durability (reference shape).
    with tempfile.TemporaryDirectory() as d:
        f = Fragment(os.path.join(d, "frag"), "i", "f", "standard", 0)
        f.open()
        t0 = time.perf_counter()
        for r, c in zip(rows.tolist(), cols.tolist()):
            f.set_bit(r, c)
        seq_dt = time.perf_counter() - t0
        f.close()

    # (b) concurrent singleton requests through the ingest queue: each
    # client thread issues one PQL SetBit request at a time and waits for
    # its durable ack (exactly the threaded-HTTP-server shape, minus HTTP).
    with tempfile.TemporaryDirectory() as d:
        h = Holder(d)
        h.open()
        h.create_index("b").create_frame("f", FrameOptions())
        ex = Executor(h, engine="numpy", write_queue=True)
        queries = [
            f'SetBit(rowID={r}, frame="f", columnID={c})'
            for r, c in zip(rows.tolist(), cols.tolist())
        ]
        ex.execute("b", queries[0])  # warm (frame/fragment creation)
        t0 = time.perf_counter()
        with ThreadPoolExecutor(n_threads) as pool:
            for _ in pool.map(lambda q: ex.execute("b", q), queries[1:]):
                pass
        q_dt = time.perf_counter() - t0
        wq = ex._write_queue
        mean_batch = wq.stat_items / max(1, wq.stat_batches)
        h.close()
    q_ops = (n - 1) / q_dt
    return {
        "metric": "setbit_ops_per_sec",
        "value": round(q_ops, 1),
        "unit": (
            f"SetBit/sec ({n_threads} concurrent clients, group-commit queue, "
            f"mean batch {mean_batch:.0f}; sequential per-op-durable fragment "
            f"rate {n / seq_dt:,.0f}/s)"
        ),
        "vs_baseline": round(q_ops / (n / seq_dt), 2),
    }


def bench_writelane() -> dict:
    """Config: native write request lane (pn_write_batch) + streaming
    columnar ingest door.

    Tiers (native vs Python A/B asserted in-run):

    - ``singleton``: canonical singleton SetBit requests through the
      NATIVE lane (``PILOSA_TPU_NO_FASTWRITE=1`` so the regex fast
      lane steps aside) vs the Python GENERAL lane (both fast lanes
      off, full parse path) — the native lane must win
      (``singleton_native_vs_general``); the default-config fast-lane
      rate rides along for context (for n=1 the regex + fused
      ``pn_array_add_logged`` crossing is already one native call, so
      the batch lane is not expected to beat it).
    - ``batched``: B-call SetBit bodies, native lane on vs off — one
      fused parse+insert+WAL crossing vs parse + vectorized batch
      (``batched_native_vs_python`` asserted > 1).
    - ``streaming``: a REAL HTTP server ingesting a packed-uint64
      column stream through ``POST .../ingest`` while concurrent read
      clients keep serving under QoS — ZERO read starvation asserted
      (no read-class sheds, every reader progresses) plus the
      sustained ingest pair rate.

    A differential gate runs in-band: the native and general lanes
    applied to the same op stream must leave byte-identical fragments.
    """
    import io
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor

    smoke = os.environ.get("BENCH_SMOKE", "").lower() in ("1", "true", "yes")
    n = int(os.environ.get("BENCH_OPS", "4000" if smoke else "20000"))
    batch = int(os.environ.get("BENCH_BATCH", "64"))
    n_rows = int(os.environ.get("BENCH_ROWS", "64"))
    stream_pairs = int(
        os.environ.get("BENCH_STREAM_PAIRS", "40000" if smoke else "400000")
    )
    n_readers = int(os.environ.get("BENCH_THREADS", "2" if smoke else "4"))

    rng = np.random.default_rng(7)
    rows = rng.integers(0, n_rows, size=n)
    cols = rng.integers(0, 1 << 20, size=n)
    rl, cl = rows.tolist(), cols.tolist()

    _ENVS = ("PILOSA_TPU_NO_WRITELANE", "PILOSA_TPU_NO_FASTWRITE")

    def with_env(env: dict):
        for k in _ENVS:
            os.environ.pop(k, None)
        os.environ.update(env)

    def run_ops(env: dict, queries: list, seed_qs: list, ops: int) -> tuple[float, bytes]:
        """Fresh holder + executor under ``env``; a seed pass (same
        containers, sibling bits: c^1) pre-creates the container set so
        the timed pass measures the steady-state lane, not first-touch
        container churn.  Returns (op/s, final fragment bytes)."""
        with_env(env)
        with tempfile.TemporaryDirectory() as d:
            h = Holder(d)
            h.open()
            h.create_index("b").create_frame("f", FrameOptions())
            ex = Executor(h, engine="numpy", qcache=None)
            for q in seed_qs:
                ex.execute("b", q)
            t0 = time.perf_counter()
            for q in queries:
                ex.execute("b", q)
            dt = time.perf_counter() - t0
            frag = h.fragment("b", "f", "standard", 0)
            buf = io.BytesIO()
            frag.write_to(buf)
            h.close()
        for k in _ENVS:
            os.environ.pop(k, None)
        return ops / dt, buf.getvalue()

    def mk_qs(rlist, clist, b):
        if b == 1:
            return [
                f'SetBit(rowID={r}, frame="f", columnID={c})'
                for r, c in zip(rlist, clist)
            ]
        return [
            "".join(
                f'SetBit(rowID={r}, frame="f", columnID={c})'
                for r, c in zip(rlist[i : i + b], clist[i : i + b])
            )
            for i in range(0, len(rlist), b)
        ]

    seed_cols = [c ^ 1 for c in cl]
    singleton_qs = mk_qs(rl, cl, 1)
    singleton_seed = mk_qs(rl, seed_cols, batch)  # fast batched seeding
    batched_qs = mk_qs(rl, cl, batch)
    batched_seed = singleton_seed

    s_native, bytes_native = run_ops(
        {"PILOSA_TPU_NO_FASTWRITE": "1"}, singleton_qs, singleton_seed, n
    )
    s_general, bytes_general = run_ops(
        {"PILOSA_TPU_NO_FASTWRITE": "1", "PILOSA_TPU_NO_WRITELANE": "1"},
        singleton_qs, singleton_seed, n,
    )
    s_fast, bytes_fast = run_ops({}, singleton_qs, singleton_seed, n)
    # Differential gate: identical op stream -> byte-identical storage,
    # whichever lane served it.
    differential_ok = bytes_native == bytes_general == bytes_fast
    assert differential_ok, "write lanes diverged: fragment bytes differ"

    b_native, bb_native = run_ops({}, batched_qs, batched_seed, n)
    b_python, bb_python = run_ops(
        {"PILOSA_TPU_NO_WRITELANE": "1"}, batched_qs, batched_seed, n
    )
    assert bb_native == bb_python, "batched lanes diverged: fragment bytes differ"

    sn_ratio = s_native / s_general
    bt_ratio = b_native / b_python
    # In-run contract: the fused native crossing must beat the Python
    # general lane on singletons and the parse+vectorized path on
    # batches.
    assert sn_ratio > 1.0, (
        f"native singleton lane did not beat the general lane: {sn_ratio:.2f}"
    )
    assert bt_ratio > 1.0, (
        f"native batch lane did not beat the python batch path: {bt_ratio:.2f}"
    )

    # -- streaming tier: ingest vs concurrent reads under QoS ------------
    import json as _json
    import urllib.error
    import urllib.request

    from pilosa_tpu.config import Config
    from pilosa_tpu.server.client import Client
    from pilosa_tpu.server.server import Server

    s_rows = rng.integers(0, n_rows, size=stream_pairs).astype(np.uint64)
    s_cols = rng.integers(0, 1 << 20, size=stream_pairs).astype(np.uint64)
    with tempfile.TemporaryDirectory() as d:
        cfg = Config(
            data_dir=d, host="127.0.0.1:0", engine="numpy", stats="expvar",
            qcache_enabled=False,
        )
        # Small write door: ingest chunks must queue behind it rather
        # than monopolize the server; reads keep their own door.
        cfg.qos_write_depth = 2
        cfg.qos_read_depth = max(4, n_readers * 2)
        srv = Server(cfg)
        srv.open()
        try:
            client = Client(srv.host)
            client.create_index("s")
            client.create_frame("s", "f")
            # Seed a few bits so readers have something to count.
            client.ingest_stream("s", "f", [1, 2, 3], [1, 2, 3])
            stop = [False]

            def reader(i: int) -> dict:
                out = {"served": 0, "shed": 0, "errors": 0}
                k = i
                while not stop[0]:
                    q = f'Count(Bitmap(rowID={k % n_rows}, frame="f"))'
                    k += 1
                    req = urllib.request.Request(
                        f"http://{srv.host}/index/s/query",
                        data=q.encode(), method="POST",
                    )
                    try:
                        with urllib.request.urlopen(req, timeout=30) as resp:
                            resp.read()
                        out["served"] += 1
                    except urllib.error.HTTPError as e:
                        e.read()
                        if e.code in (429, 503):
                            out["shed"] += 1
                        else:
                            out["errors"] += 1
                    except OSError:
                        out["errors"] += 1
                return out

            with ThreadPoolExecutor(n_readers + 1) as pool:
                futs = [pool.submit(reader, i) for i in range(n_readers)]
                t0 = time.perf_counter()
                res = client.ingest_stream(
                    "s", "f", s_rows, s_cols, chunk_pairs=16384
                )
                ingest_dt = time.perf_counter() - t0
                stop[0] = True
                reads = [f.result() for f in futs]
            assert res["done"], "streamed ingest did not complete"
            v = _json.loads(
                urllib.request.urlopen(f"http://{srv.host}/debug/vars").read()
            )
            read_sheds = int(v.get("qos.shed.read", 0))
            # Zero read starvation: ingest backpressure lands on the
            # WRITE door; every reader kept serving and no read shed.
            assert read_sheds == 0, f"reads shed during ingest: {read_sheds}"
            assert all(r["served"] > 0 for r in reads), (
                f"a reader starved during ingest: {reads}"
            )
            stream_rate = stream_pairs / ingest_dt
            reads_served = sum(r["served"] for r in reads)
        finally:
            srv.close()

    return {
        "metric": "writelane_batched_native_vs_python",
        "value": round(bt_ratio, 2),
        "unit": (
            f"x vs python batch path (B={batch}; singleton native "
            f"{s_native:,.0f}/s vs general {s_general:,.0f}/s = "
            f"x{sn_ratio:.2f}, fast lane {s_fast:,.0f}/s; streaming "
            f"{stream_rate:,.0f} pairs/s with {reads_served} concurrent "
            f"reads, 0 read sheds)"
        ),
        "tiers": {
            "singleton_native_ops": round(s_native, 1),
            "singleton_general_ops": round(s_general, 1),
            "singleton_fast_ops": round(s_fast, 1),
            "singleton_native_vs_general": round(sn_ratio, 2),
            "batched_native_ops": round(b_native, 1),
            "batched_python_ops": round(b_python, 1),
            "batched_native_vs_python": round(bt_ratio, 2),
            "stream_pairs_per_s": round(stream_rate, 1),
            "stream_reads_served": reads_served,
            "stream_read_sheds": 0,
            "differential_ok": True,
        },
    }


def bench_topn() -> dict:
    """Config 3: TopN over a ranked frame — candidate scoring via the
    batched intersection-count kernel (fragment.go:493-625 analog)."""
    n_rows = int(os.environ.get("BENCH_TOPN_ROWS", "2048"))
    iters = int(os.environ.get("BENCH_ITERS", "400"))
    import jax
    import jax.numpy as jnp
    from jax import lax

    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE

    rng = np.random.default_rng(3)
    rows = rng.integers(0, 1 << 32, size=(n_rows, WORDS_PER_SLICE), dtype=np.uint32)
    src = rng.integers(0, 1 << 32, size=(WORDS_PER_SLICE,), dtype=np.uint32)
    masks = rng.integers(0, 1 << 32, size=(iters,), dtype=np.uint32)

    # Scan-chained stream with digest timing (see the headline config):
    # full per-row scores stay materialized in HBM; fetching them through
    # the tunnel (~3 MB here) would dominate the timed region.
    @jax.jit
    def run_stream(rws, s, ms):
        def step(carry, m):
            inter = jnp.bitwise_and(rws, jnp.bitwise_xor(s, m))
            return carry, jnp.sum(
                lax.population_count(inter).astype(jnp.int32), axis=1
            )

        out = lax.scan(step, 0, ms)[1]
        return out, out.astype(jnp.int64).sum()

    drows, dsrc = jax.device_put(rows), jax.device_put(src)
    dmasks = jax.device_put(masks)
    out_dev, _ = run_stream(drows, dsrc, dmasks)  # warm + compile

    def timed():
        out_d, digest = run_stream(drows, dsrc, dmasks)
        np.asarray(digest)
        return out_d

    dt, out_dev = _best_of_runs(timed)
    out = np.asarray(out_dev)
    dt /= iters
    from pilosa_tpu.roaring import _POPCNT8

    base_iters = max(1, min(2, iters))
    t0 = time.perf_counter()
    for i in range(base_iters):
        base = _POPCNT8[(rows & (src ^ masks[i])).view(np.uint8)].reshape(n_rows, -1).sum(axis=1)
    base_dt = (time.perf_counter() - t0) / base_iters
    assert np.array_equal(out[base_iters - 1], base)
    return {
        "metric": "topn_candidate_scan_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": f"rows/sec scored vs src ({n_rows} rows x 2^20 cols, backend {jax.default_backend()})",
        "vs_baseline": round(base_dt / dt, 2),
    }


def bench_union64() -> dict:
    """Config 4: multi-slice Union+Count mapReduce over 64 slices.

    Same timing methodology as the headline config: all iterations are
    chained inside one jitted ``lax.scan`` and timing stops when the
    results land on the host, so the remote-tunnel round trip is paid
    once for the whole stream instead of once per query.  Each scan step
    XORs one operand with a distinct 32-bit mask so every step's union
    is a different computation XLA cannot hoist out of the loop (it
    costs one extra elementwise op in a bandwidth-bound kernel).
    """
    n_slices = int(os.environ.get("BENCH_SLICES", "64"))
    iters = int(os.environ.get("BENCH_ITERS", "16000"))
    import jax
    import jax.numpy as jnp
    from jax import lax

    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE

    rng = np.random.default_rng(4)
    a = rng.integers(0, 1 << 32, size=(n_slices, WORDS_PER_SLICE), dtype=np.uint32)
    b = rng.integers(0, 1 << 32, size=(n_slices, WORDS_PER_SLICE), dtype=np.uint32)
    masks = rng.integers(0, 1 << 32, size=(iters,), dtype=np.uint32)

    @jax.jit
    def run_stream(x, y, ms):
        def step(carry, m):
            u = jnp.bitwise_or(jnp.bitwise_xor(x, m), y)
            return carry, jnp.sum(lax.population_count(u).astype(jnp.int64))

        out = lax.scan(step, 0, ms)[1]
        return out, out.sum()

    da, db = jax.device_put(a), jax.device_put(b)
    dmasks = jax.device_put(masks)
    got_dev, _ = run_stream(da, db, dmasks)  # warm + compile

    def timed():
        out_d, digest = run_stream(da, db, dmasks)
        np.asarray(digest)
        return out_d

    dt, got_dev = _best_of_runs(timed)
    got = np.asarray(got_dev)
    dt /= iters
    from pilosa_tpu.roaring import _POPCNT8

    base_iters = max(1, min(3, iters))
    t0 = time.perf_counter()
    for i in range(base_iters):
        want = int(_POPCNT8[((a ^ masks[i]) | b).view(np.uint8)].sum())
    base_dt = (time.perf_counter() - t0) / base_iters
    assert got[base_iters - 1] == want
    cols_per_sec = n_slices * (1 << 20) / dt
    return {
        "metric": "union_count_cols_per_sec",
        "value": round(cols_per_sec, 1),
        "unit": f"columns/sec unioned+counted ({n_slices} slices, backend {jax.default_backend()})",
        "vs_baseline": round(base_dt / dt, 2),
    }


def bench_timerange() -> dict:
    """Config 5: time-quantum Range — OR-reduce the YMDH view cover of a
    1-year range (time.go:95-167 analog; ~15 views) then popcount."""
    iters = int(os.environ.get("BENCH_ITERS", "32768"))
    n_views = 15  # typical cover size for a 1-year [start, end) at YMDH
    import jax
    import jax.numpy as jnp
    from jax import lax

    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE

    rng = np.random.default_rng(5)
    views = rng.integers(0, 1 << 32, size=(n_views, WORDS_PER_SLICE), dtype=np.uint32)
    masks = rng.integers(0, 1 << 32, size=(iters,), dtype=np.uint32)

    # Scan-chained stream (see bench_union64 docstring for why): one
    # dispatch + one host fetch for the whole stream; per-step masks keep
    # every Range a distinct computation.  Each step evaluates a BATCH of
    # range queries (vmapped over masks) — the executor's query-batch
    # fusion shape — so the fixed per-step scan cost amortizes across a
    # view cover that is otherwise only ~2 MB of HBM traffic.
    step_batch = min(int(os.environ.get("BENCH_BATCH", "128")), iters)
    iters -= iters % step_batch
    masks = masks[:iters]

    @jax.jit
    def run_stream(v, ms):
        def one(m):
            acc = lax.reduce(jnp.bitwise_xor(v, m), np.uint32(0), lax.bitwise_or, (0,))
            return jnp.sum(lax.population_count(acc).astype(jnp.int64))

        def step(carry, mrow):
            return carry, jax.vmap(one)(mrow)

        out = lax.scan(step, 0, ms.reshape(-1, step_batch))[1].reshape(-1)
        return out, out.sum()

    dv = jax.device_put(views)
    dmasks = jax.device_put(masks)
    got_dev, _ = run_stream(dv, dmasks)  # warm + compile

    def timed():
        out_d, digest = run_stream(dv, dmasks)
        np.asarray(digest)
        return out_d

    dt, got_dev = _best_of_runs(timed)
    got = np.asarray(got_dev)
    dt /= iters
    from pilosa_tpu.roaring import _POPCNT8

    base_iters = max(1, min(3, iters))
    t0 = time.perf_counter()
    for i in range(base_iters):
        acc = views[0] ^ masks[i]
        for j in range(1, n_views):
            acc |= views[j] ^ masks[i]
        want = int(_POPCNT8[acc.view(np.uint8)].sum())
    base_dt = (time.perf_counter() - t0) / base_iters
    assert got[base_iters - 1] == want
    return {
        "metric": "timerange_union_views_per_sec",
        "value": round(n_views / dt, 1),
        "unit": f"views/sec OR-reduced+counted ({n_views}-view YMDH cover, backend {jax.default_backend()})",
        "vs_baseline": round(base_dt / dt, 2),
    }


def bench_executor() -> dict:
    """End-to-end product path: PQL text -> parser -> Executor ->
    fused device dispatch (_fuse_count_pair_batch) -> results.

    Unlike the headline config (raw kernel throughput), this measures the
    whole single-node product stack the way a client drives it: each
    request is a batch of Count(Intersect(Bitmap, Bitmap)) calls in one
    PQL string, against a Holder-backed frame whose rows live in the
    fragment device cache after warmup.  vs_baseline compares the same
    requests through the numpy engine (the reference-style CPU path).
    """
    n_slices = int(os.environ.get("BENCH_SLICES", "8"))
    n_rows = int(os.environ.get("BENCH_ROWS", "32"))
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    # Enough requests that cold-start (first uncached matrices + the one
    # Gram build) amortizes; steady state is ONE native gram-lane call
    # per request (~0.25ms), so short runs would mostly time the few
    # remaining warm-up stragglers.
    iters = int(os.environ.get("BENCH_ITERS", "240"))
    bits_per_row = int(os.environ.get("BENCH_BITS_PER_ROW", "20000"))
    import tempfile

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.pilosa import SLICE_WIDTH

    rng = np.random.default_rng(11)

    def build_query(pairs):
        return " ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for a, b in pairs
        )

    all_pairs = rng.integers(0, n_rows, size=(iters, batch, 2))
    queries = [build_query(p.tolist()) for p in all_pairs]

    with tempfile.TemporaryDirectory() as d:
        h = Holder(d)
        h.open()
        idx = h.create_index("bench")
        idx.create_frame("f", FrameOptions())
        fr = idx.frame("f")
        for s in range(n_slices):
            rows = np.repeat(np.arange(n_rows, dtype=np.uint64), bits_per_row)
            cols = rng.integers(0, SLICE_WIDTH, size=len(rows)).astype(
                np.uint64
            ) + np.uint64(s * SLICE_WIDTH)
            fr.import_bits(rows, cols)

        # write_queue=True is the SERVER's executor configuration; it also
        # enables read coalescing, so the threaded variant measures what
        # concurrent clients actually hit.
        ex = Executor(h, write_queue=True)
        backend = ex.engine.name
        # Warm past the strategy ladder: request 1 builds + caches the row
        # matrix, request 2+ upgrade it to the Gram (single-flight build),
        # after which steady state is host-side count lookups.  Timing
        # from a cold cache would mostly measure the one-time matrix
        # upload + Gram matmul, not the serving rate.
        for q in queries[: min(4, len(queries))]:
            ex.execute("bench", q)
        # Drive like a loaded server: concurrent requests overlap parse
        # (CPU) with device dispatch + result fetch, exactly as the
        # threaded HTTP server does.  BENCH_THREADS=1 for pure latency.
        n_threads = int(os.environ.get("BENCH_THREADS", "8"))
        from concurrent.futures import ThreadPoolExecutor

        t0 = time.perf_counter()
        if n_threads > 1:
            with ThreadPoolExecutor(n_threads) as pool:
                for _ in pool.map(lambda q: ex.execute("bench", q), queries):
                    pass
        else:
            for q in queries:
                ex.execute("bench", q)
        dt = time.perf_counter() - t0
        qps = iters * batch / dt

        ex_np = Executor(h, engine="numpy")
        base_iters = max(1, min(3, iters))
        ex_np.execute("bench", queries[0])  # warm: host matrix-cache build
        t0 = time.perf_counter()
        for q in queries[:base_iters]:
            base_out = ex_np.execute("bench", q)
        base_dt = time.perf_counter() - t0
        base_qps = base_iters * batch / base_dt
        # Correctness gate: the fused engine path must agree with the numpy
        # product path on one of the timed queries.
        assert ex.execute("bench", queries[base_iters - 1]) == base_out
        h.close()
    return {
        "metric": "executor_intersect_count_qps",
        "value": round(qps, 1),
        "unit": f"PQL queries/sec end-to-end ({n_slices} slices, batch {batch}, engine {backend})",
        "vs_baseline": round(qps / base_qps, 2),
    }


def bench_executor_gather() -> dict:
    """Product-path GATHER-REGIME shape: steady-state PQL pair-count
    requests over a TALL distinct-row working set (the reference's real
    hot-path shape, executor.go:1115-1244: many distinct rows rather
    than 64 hot ones).

    Since round 4 the executor serves this shape from the chunked
    Gram-at-scale lane (bitwise.pair_gram streams (slice, word-chunk)
    steps, so the Gram has no row ceiling up to PILOSA_TPU_GRAM_ROWS_MAX
    = 4096): after a one-time build, every request is answered by
    host-side native count lookups (pn_gram_counts) with ZERO per-request
    device round trips — the ~100 ms tunnel RTT that bounded round 3's
    2-2.8k q/s is off the steady-state path entirely.

    value       = product-path steady q/s (warm Gram, sequential client).
    vs_baseline = product path vs the NO_GRAM slice-major gather lane
                  (round 3's product path) with a sequential client.
    The unit string records the forced-NO_GRAM lane tiers too: row-major
    and slice-major, sequential AND a 16-thread client (the concurrency
    that amortizes this environment's tunnel RTT; kernel-level lane
    records live in intersect_count_4krows)."""
    n_rows = int(os.environ.get("BENCH_ROWS", "4096"))
    n_slices = int(os.environ.get("BENCH_SLICES", "4"))
    batch = int(os.environ.get("BENCH_BATCH", "512"))
    n_queries = int(os.environ.get("BENCH_ITERS", "8"))
    bits_per_row = int(os.environ.get("BENCH_BITS_PER_ROW", "20"))
    repeats = 3
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    import pilosa_tpu.engine as engine_mod
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.pilosa import SLICE_WIDTH

    rng = np.random.default_rng(77)
    with tempfile.TemporaryDirectory() as d:
        h = Holder(d)
        h.open()
        h.create_index("p").create_frame("f", FrameOptions())
        fr = h.index("p").frame("f")
        rows = np.repeat(np.arange(n_rows, dtype=np.uint64), bits_per_row)
        for s in range(n_slices):
            cols = rng.integers(0, SLICE_WIDTH, size=len(rows)).astype(
                np.uint64
            ) + np.uint64(s * SLICE_WIDTH)
            fr.import_bits(rows, cols)

        def build_q(seed):
            # All-distinct operands: want = 2 * pairs, past the resident
            # kernel's predicate.
            perm = np.random.default_rng(seed).permutation(n_rows)
            return " ".join(
                f'Count(Intersect(Bitmap(rowID={int(perm[2 * i])}, frame="f"), '
                f'Bitmap(rowID={int(perm[2 * i + 1])}, frame="f")))'
                for i in range(batch // 2)
            )

        qs = [build_q(i) for i in range(n_queries)]
        total = n_queries * (batch // 2)

        def steady_rates(ex):
            """(sequential q/s, 16-thread q/s) after a full warmup.

            The 16-thread tier is SUSTAINED load — 16 persistent client
            threads each looping the request set — not a pool.map over
            the 8 distinct requests: with the round-5 native serve lane
            a request costs ~100 us, so a fresh-pool 8-item map would
            time thread spawn + handoff, not serving (measured 20x
            under-report on the 1024x4 shape).
            """
            import threading

            for q in qs:  # pass 1: rows page in, kernels compile
                ex.execute("p", q)
            for q in qs:  # pass 2: caches (Gram) build on stable residency
                ex.execute("p", q)
            t0 = time.perf_counter()
            for _ in range(repeats):
                for q in qs:
                    ex.execute("p", q)
            seq = repeats * total / (time.perf_counter() - t0)
            n_threads = 16
            # Size the sustained run from the measured sequential rate:
            # ~3 s of aggregate work regardless of which lane is being
            # measured (the NO_GRAM device tiers are ~1000x slower than
            # the native serve lane; a fixed loop count would run them
            # for minutes).
            loops = max(1, int(seq * 3.0 / (n_threads * total)))

            def client():
                for _ in range(loops):
                    for q in qs:
                        ex.execute("p", q)

            threads = [threading.Thread(target=client) for _ in range(n_threads)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            thr = n_threads * loops * total / (time.perf_counter() - t0)
            return seq, thr

        # write_queue=True is the SERVER's executor configuration; its
        # serve-queue read coalescing merges concurrent flat-lane
        # requests into one vectorized evaluation (16-thread Gram
        # serving measured +76% vs the bare executor).
        ex = Executor(h, write_queue=True)
        backend = ex.engine.name
        qps, qps_thr = steady_rates(ex)
        # Forced-NO_GRAM lane tiers: row-major and slice-major gather —
        # measured WITHOUT the serve queue: coalescing serializes all
        # clients behind one leader's device dispatches, which is right
        # when serving is host-bound (Gram lookups) but destroys the
        # concurrent-RTT overlap that is the whole point of the
        # 16-thread tier on eager device lanes (measured: x16 7.3k
        # without queue vs 1.0k with, through this tunnel).
        prior_no_gram = os.environ.get("PILOSA_TPU_NO_GRAM")
        os.environ["PILOSA_TPU_NO_GRAM"] = "1"
        orig = engine_mod.JaxEngine.prefer_rowmajor
        try:
            rm_seq, rm_thr = steady_rates(Executor(h))
            engine_mod.JaxEngine.prefer_rowmajor = lambda self, *a: False
            sm_seq, sm_thr = steady_rates(Executor(h))
        finally:
            engine_mod.JaxEngine.prefer_rowmajor = orig
            if prior_no_gram is None:
                del os.environ["PILOSA_TPU_NO_GRAM"]
            else:
                os.environ["PILOSA_TPU_NO_GRAM"] = prior_no_gram
        # Correctness gate vs numpy on one request.
        assert ex.execute("p", qs[0]) == Executor(h, engine="numpy").execute("p", qs[0])
        h.close()
    return {
        "metric": "executor_gather_qps",
        "value": round(qps, 1),
        "unit": (
            f"PQL queries/sec end-to-end, gather-regime shape ({n_rows} distinct "
            f"rows x {n_slices} slices, batch {batch // 2}, warm chunked-Gram "
            f"product lane, server executor config (single-call native serve "
            f"lane, GIL released), sequential client; {qps_thr:,.0f} q/s "
            f"16-thread sustained; "
            f"NO_GRAM tiers: row-major {rm_seq:,.0f} seq / {rm_thr:,.0f} x16, "
            f"slice-major {sm_seq:,.0f} seq / {sm_thr:,.0f} x16 (tunnel-RTT-"
            f"bound; kernel-level lane record in intersect_count_4krows), "
            f"engine {backend})"
        ),
        "vs_baseline": round(qps / sm_seq, 2),
    }


def bench_range_executor() -> dict:
    """End-to-end fused Range path: batched PQL Count(Range(...)) requests
    through the Executor — parser -> fused multi-view matrix ->
    gather-OR-popcount kernel (the time-quantum dashboard workload;
    time.go:95-167 + executor.go:498-554 analog).  vs_baseline compares
    the same requests through the numpy engine."""
    n_slices = int(os.environ.get("BENCH_SLICES", "4"))
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    iters = int(os.environ.get("BENCH_ITERS", "40"))
    bits = int(os.environ.get("BENCH_BITS", "20000"))
    import tempfile
    from datetime import datetime

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.pilosa import SLICE_WIDTH

    rng = np.random.default_rng(13)
    n_rows = 8
    stamps = [
        datetime(2017, m, d, hh)
        for m in range(1, 13) for d in (1, 15) for hh in (0, 12)
    ]
    # Workload: a dashboard-style span pool — 4 fixed "widget" ranges plus
    # 24 randomized day-aligned spans drawn once.  Warmup requests build
    # the multi-view matrix and dispatch the gather-OR kernel per new
    # cover; steady state serves repeats from the host-side cover memo
    # with one device dispatch per request carrying that request's
    # first-seen covers.  The kernel's raw rate has its own config
    # (BENCH_CONFIG=timerange); under the remote tunnel (~70 ms RTT) an
    # unbounded-diversity stream would only measure upload latency, and
    # the executor caps fusion at its matrix row budget anyway.
    pool = [
        ("2017-01-01T00:00", "2018-01-01T00:00"),
        ("2017-02-01T00:00", "2017-07-15T12:00"),
        ("2017-03-01T00:00", "2017-04-01T00:00"),
        ("2017-06-10T00:00", "2017-06-20T00:00"),
    ]
    # Short day-aligned spans inside Jan-Feb: distinct covers without
    # blowing the fused path's (view, row) combo budget.
    for _ in range(24):
        m1 = int(rng.integers(1, 3))
        d1 = int(rng.integers(1, 28))
        dur = int(rng.integers(1, 22))
        m2, d2 = m1, d1 + dur
        if d2 > 28:
            m2, d2 = m1 + 1, d2 - 28
        pool.append((f"2017-{m1:02d}-{d1:02d}T00:00", f"2017-{m2:02d}-{d2:02d}T00:00"))

    def build_query(rows_, spans_):
        return " ".join(
            f'Count(Range(rowID={r}, frame="t", start="{s}", end="{en}"))'
            for r, (s, en) in zip(rows_, spans_)
        )

    queries = [
        build_query(
            rng.integers(0, n_rows, size=batch).tolist(),
            [pool[int(rng.integers(0, len(pool)))] for _ in range(batch)],
        )
        for _ in range(iters)
    ]

    with tempfile.TemporaryDirectory() as d:
        h = Holder(d)
        h.open()
        idx = h.create_index("bench")
        idx.create_frame("t", FrameOptions(time_quantum="YMD"))
        fr = idx.frame("t")
        rows = rng.integers(0, n_rows, size=bits).astype(np.uint64)
        cols = rng.integers(0, n_slices * SLICE_WIDTH, size=bits).astype(np.uint64)
        ts = [stamps[i] for i in rng.integers(0, len(stamps), size=bits)]
        fr.import_bits(rows, cols, ts)

        ex = Executor(h)
        backend = ex.engine.name
        # Warm over the whole query set: the multi-view matrix reaches its
        # final capacity, kernel shapes compile, and repeated covers land
        # in the memo — the timed loop then measures the dashboard steady
        # state (parse -> fused match -> memo/kernel), which is what a
        # refresh-driven client sees.  Kernel-rate-per-cover has its own
        # config (BENCH_CONFIG=timerange).
        for q in queries:
            ex.execute("bench", q)
        t0 = time.perf_counter()
        for q in queries:
            ex.execute("bench", q)
        dt = time.perf_counter() - t0
        qps = iters * batch / dt

        # Baseline: the same calls executed ONE AT A TIME on the numpy
        # engine — per-call view gathers and OR chains, the reference-style
        # CPU executor shape (fusion and the cover memo only engage on
        # batched requests).
        ex_np = Executor(h, engine="numpy")
        import re

        base_calls = re.findall(r"Count\(Range\([^)]*\)\)", queries[0])
        base_n = min(16, len(base_calls))
        ex_np.execute("bench", base_calls[0])  # warm row caches
        t0 = time.perf_counter()
        base_out = [ex_np.execute("bench", q)[0] for q in base_calls[:base_n]]
        base_dt = time.perf_counter() - t0
        base_qps = base_n / base_dt
        # Correctness gate: fused results must match sequential execution.
        assert ex.execute("bench", queries[0])[:base_n] == base_out
        h.close()
    return {
        "metric": "range_executor_qps",
        "value": round(qps, 1),
        "unit": (
            f"PQL Count(Range) queries/sec, dashboard steady state "
            f"({n_slices} slices, batch {batch}, engine {backend})"
        ),
        "vs_baseline": round(qps / base_qps, 2),
    }


def bench_mixed() -> dict:
    """Mixed read/write serving tier: warm-Gram pair-count batches with
    single-bit SetBit writes interleaved, at 95/5 and 50/50 request
    mixes.  Measures the warm-state REPAIR lane (delta-patched row
    matrices + rank-k Gram updates) against forced
    invalidate-and-rebuild (PILOSA_TPU_REPAIR_ROWS_MAX=0) on the same
    request stream; per-mix steady qps, the latency of the read
    immediately following a write (the repair-vs-rebuild split), and the
    pool repair count land in the ``tiers`` list.  Every write targets a
    column range the import never touches, so each one really mutates
    storage and really invalidates (or patches) the warm state.
    BENCH_SMOKE=1 shrinks every shape to run under CI tier-1 time
    budgets on CPU, exercising the patch lane end to end."""
    smoke = os.environ.get("BENCH_SMOKE", "").lower() in ("1", "true", "yes")
    n_slices = int(os.environ.get("BENCH_SLICES", "2" if smoke else "4"))
    n_rows = int(os.environ.get("BENCH_ROWS", "16" if smoke else "64"))
    batch = int(os.environ.get("BENCH_BATCH", "8" if smoke else "128"))
    n_requests = int(os.environ.get("BENCH_ITERS", "30" if smoke else "400"))
    bits_per_row = int(
        os.environ.get("BENCH_BITS_PER_ROW", "50" if smoke else "20000")
    )
    import tempfile

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.pilosa import SLICE_WIDTH

    rng = np.random.default_rng(23)
    reserve = 4096  # import keeps these top columns free for the writes

    def build_read(seed):
        prs = np.random.default_rng(seed).integers(0, n_rows, size=(batch, 2))
        return " ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for a, b in prs.tolist()
        )

    read_qs = [build_read(s) for s in range(4)]
    state = {"engine": "?"}

    def run_mix(write_every: int, repair_on: bool, burst: int = 1,
                n_req: int = 0) -> dict:
        """One mixed-traffic run.  ``burst > 1`` switches the 50/50
        schedule from strict alternation to coalescing bursts: ``burst``
        back-to-back writes followed by ``burst`` reads — the whole
        burst's dirty rows accumulate in the ledger/journals and the
        FIRST read dispatches ONE deferred repair for the union (one
        pool rewrite + one rank-k Gram update per burst, not per
        write)."""
        prior = os.environ.get("PILOSA_TPU_REPAIR_ROWS_MAX")
        if not repair_on:
            os.environ["PILOSA_TPU_REPAIR_ROWS_MAX"] = "0"
        n_req = n_req or n_requests
        try:
            with tempfile.TemporaryDirectory() as d:
                h = Holder(d)
                h.open()
                h.create_index("m").create_frame("f", FrameOptions())
                fr = h.index("m").frame("f")
                rows = np.repeat(np.arange(n_rows, dtype=np.uint64), bits_per_row)
                for s in range(n_slices):
                    cols = rng.integers(
                        0, SLICE_WIDTH - reserve, size=len(rows)
                    ).astype(np.uint64) + np.uint64(s * SLICE_WIDTH)
                    fr.import_bits(rows, cols)
                ex = Executor(h)
                state["engine"] = ex.engine.name
                for q in read_qs:  # pass 1: matrices page in, jit compiles
                    ex.execute("m", q)
                for q in read_qs:  # pass 2: the Gram (and serve lane) arm
                    ex.execute("m", q)
                wcount = 0
                calls = 0
                lat_post_write: list = []
                lat_other: list = []
                last_was_write = False
                t0 = time.perf_counter()
                for i in range(n_req):
                    if burst > 1:
                        is_write = i % (2 * burst) < burst  # W^b R^b cycles
                    else:
                        is_write = write_every and i % write_every == write_every - 1
                    if is_write:
                        r = wcount % n_rows
                        c = (SLICE_WIDTH - reserve) + (wcount // n_rows) % reserve
                        ex.execute("m", f'SetBit(rowID={r}, frame="f", columnID={c})')
                        wcount += 1
                        calls += 1
                        last_was_write = True
                    else:
                        t1 = time.perf_counter()
                        ex.execute("m", read_qs[i % len(read_qs)])
                        dt1 = time.perf_counter() - t1
                        (lat_post_write if last_was_write else lat_other).append(dt1)
                        calls += batch
                        last_was_write = False
                dt = time.perf_counter() - t0
                # Correctness gate: warm-lane counts must match the numpy
                # sequential path AFTER the interleaved writes (the
                # read-your-writes contract the repair must not break).
                want = Executor(h, engine="numpy").execute("m", read_qs[0])
                got = ex.execute("m", read_qs[0])
                assert got == want, "mixed-lane counts diverged from numpy"
                repairs = sum(
                    p.stat_repairs for p in ex._matrix_cache.values()
                )
                patch_planes = sum(
                    p.stat_patch_planes for p in ex._matrix_cache.values()
                )
                h.close()
            return {
                "qps": calls / dt,
                "post_write_ms": (
                    1e3 * float(np.mean(lat_post_write)) if lat_post_write else None
                ),
                "steady_ms": 1e3 * float(np.mean(lat_other)) if lat_other else None,
                "repairs": repairs,
                "patch_planes": patch_planes,
            }
        finally:
            if prior is None:
                os.environ.pop("PILOSA_TPU_REPAIR_ROWS_MAX", None)
            else:
                os.environ["PILOSA_TPU_REPAIR_ROWS_MAX"] = prior

    # Coalescing tiers: 50/50 at write-burst sizes 8 and 64 — each
    # burst's writes batch into ONE deferred repair dispatch, so
    # qps/repairs scale with the burst (requests scale so every tier
    # sees several full cycles).
    tiers = []
    plan = [
        ("mixed_95_5", 20, 1, 0),
        ("mixed_50_50", 2, 1, 0),
        ("mixed_50_50_b8", 2, 8, max(n_requests, 8 * 8)),
        ("mixed_50_50_b64", 2, 64, max(n_requests, 8 * 64)),
    ]
    for name, write_every, burst, n_req in plan:
        rep = run_mix(write_every, True, burst=burst, n_req=n_req)
        reb = run_mix(write_every, False, burst=burst, n_req=n_req)
        tiers.append({
            "tier": name,
            "qps": round(rep["qps"], 1),
            "rebuild_qps": round(reb["qps"], 1),
            "speedup": round(rep["qps"] / reb["qps"], 2),
            "repair_post_write_ms": (
                round(rep["post_write_ms"], 3) if rep["post_write_ms"] else None
            ),
            "rebuild_post_write_ms": (
                round(reb["post_write_ms"], 3) if reb["post_write_ms"] else None
            ),
            "steady_ms": round(rep["steady_ms"], 3) if rep["steady_ms"] else None,
            "repairs": rep["repairs"],
            "patch_planes": rep["patch_planes"],
        })
    head = tiers[0]
    return {
        "metric": "mixed_rw_qps",
        "value": head["qps"],
        "unit": (
            f"PQL calls/sec, 95/5 read/write mix ({n_slices} slices x "
            f"{n_rows} rows, batch {batch}, warm-state repair lane vs "
            f"invalidate-and-rebuild x{head['speedup']}; 50/50 mix "
            f"{tiers[1]['qps']:,.0f} calls/s (x{tiers[1]['speedup']} vs "
            f"rebuild), engine {state['engine']})"
        ),
        "vs_baseline": head["speedup"],
        "tiers": tiers,
    }


# v5e single-chip HBM bandwidth roofline (bytes/sec) for bandwidth_util
# accounting; override for other parts (v4: ~1.2e12, v5p: ~2.8e12).
HBM_ROOFLINE = float(os.environ.get("BENCH_HBM_ROOFLINE", str(819e9)))


def bench_intersect_stream() -> dict:
    """Headline shape PAST device memory: the slice axis streams through
    HBM in chunks (the executor's slice-streaming regime).  Default 2048
    slices x 64 rows = 16 GiB of packed bitmaps — larger than one v5e
    chip's HBM — with per-query partial counts accumulated across chunk
    steps exactly as the executor's streaming branch does.

    What is measured here is the DEVICE half of that regime: each of the
    n_chunks logical chunks is served by one resident 2 GiB physical
    chunk (the HBM read traffic per pass — the thing the chip actually
    does per chunk — is identical whether the bytes changed since the
    last pass; only the host->device refill differs).  The refill side
    cannot be measured through this environment's ~4 MiB/s tunnel — a
    17 GiB pass uploads for >60 min, which is how the r02 attempt died —
    so the tunnel upload rate is measured separately on a small block and
    reported in the unit string; on real hardware refills ride PCIe at
    10-60 GB/s and double-buffer behind this compute.
    """
    n_slices = int(os.environ.get("BENCH_SLICES", "2048"))
    n_rows = int(os.environ.get("BENCH_ROWS", "64"))
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    iters = int(os.environ.get("BENCH_ITERS", "8"))
    chunk_slices = int(os.environ.get("BENCH_CHUNK_SLICES", "256"))

    import jax
    import jax.numpy as jnp
    from jax import lax

    from pilosa_tpu.ops import dispatch
    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE
    from pilosa_tpu.ops.pallas_kernels import fused_resident_count2

    W = WORDS_PER_SLICE
    rng = np.random.default_rng(42)
    n_chunks = (n_slices + chunk_slices - 1) // chunk_slices
    # One pair batch per (outer step, chunk step): in the real streaming
    # regime every chunk serves the SAME batch, but an invariant kernel
    # call inside the chunk scan is loop-hoisted by XLA (first cut of
    # this bench "measured" 981 GB/s — above the roofline — because only
    # one chunk was ever read); distinct pairs per chunk step keep the
    # identical per-chunk HBM traffic while making each step a distinct
    # computation.
    all_pairs = rng.integers(
        0, n_rows, size=(iters, n_chunks, batch, 2), dtype=np.int32
    )

    @jax.jit
    def gen_chunk(key):
        return jax.random.bits(
            key, (chunk_slices, n_rows, W // 128, 128), jnp.uint32
        )

    dchunk = gen_chunk(jax.random.PRNGKey(42))
    dpairs = jax.device_put(all_pairs)

    interp = jax.default_backend() != "tpu"  # CPU smoke runs

    @jax.jit
    def run_stream(chunk, pairs_stream):
        # Outer scan: one step per query batch; inner scan: one step per
        # logical chunk.  Per-chunk partials come back as scan OUTPUTS
        # and the cross-chunk int64 accumulation happens host-side: on
        # device (no x64) jnp.int64 silently truncates to int32, which
        # overflows past ~16 chunks of full-density counts (the executor's
        # streaming regime accumulates per-chunk engine results host-side
        # the same way).
        def per_batch(carry, prs_chunks):
            def per_chunk(c2, prs):
                return c2, fused_resident_count2(
                    "and", chunk, prs, interpret=interp
                )

            return carry, lax.scan(per_chunk, 0, prs_chunks)[1]  # [n_chunks, B]

        out = lax.scan(per_batch, 0, pairs_stream)[1]  # [iters, n_chunks, batch]
        return out, out.sum()  # digest: sync only (int32 wrap is fine)

    out_dev, _ = run_stream(dchunk, dpairs)  # warm + compile

    def timed():
        out_d, digest = run_stream(dchunk, dpairs)
        np.asarray(digest)
        return out_d

    dt, out_dev = _best_of_runs(timed, default_runs=3)
    out = np.asarray(out_dev).astype(np.int64).sum(axis=1)  # [iters, batch]
    qps = iters * batch / dt
    bytes_read = iters * n_chunks * chunk_slices * n_rows * W * 4
    hbm_gbps = bytes_read / dt / 1e9

    # Tunnel upload rate on a 64 MiB block (the environment's refill
    # bound; real deployments refill over PCIe).
    blk = np.zeros((64 << 20) // 4, dtype=np.uint32)
    jax.device_put(blk).block_until_ready()
    t0 = time.perf_counter()
    jax.device_put(blk).block_until_ready()
    upload_mbps = 64 / (time.perf_counter() - t0)

    # Ground truth: outer step 0's accumulated counts = sum over chunk
    # steps of that step's per-chunk counts; gate the first chunk batch's
    # slice-0 partial against numpy too.
    from pilosa_tpu.roaring import _POPCNT8

    s0 = np.asarray(dchunk[:1]).reshape(n_rows, W)
    p = all_pairs[0, 0]
    part0 = _POPCNT8[(s0[p[:, 0]] & s0[p[:, 1]]).view(np.uint8)].reshape(
        batch, -1
    ).sum(axis=1, dtype=np.int64)
    rest = np.asarray(
        dispatch.gather_count("and", dchunk[1:], jnp.asarray(p), allow_gram=False)
    ).astype(np.int64)
    want = np.zeros(batch, dtype=np.int64)
    for k in range(n_chunks):
        want += np.asarray(
            dispatch.gather_count(
                "and", dchunk, jnp.asarray(all_pairs[0, k]), allow_gram=False
            )
        ).astype(np.int64)
        if k == 0:
            assert np.array_equal(want - rest, part0), "slice-0 partial mismatch"
    assert np.array_equal(out[0], want), "stream accumulation mismatch"

    cols = n_slices * (1 << 20)
    return {
        "metric": "intersect_count_stream_qps",
        "value": round(qps, 1),
        "unit": (
            f"queries/sec over {cols/1e9:.2f}B columns ({n_slices} slices, "
            f"{n_rows} rows, {n_chunks}x{chunk_slices}-slice chunks, "
            f"{n_chunks * chunk_slices * n_rows * W * 4 / 2**30:.0f} GiB/pass read at "
            f"{hbm_gbps:.0f} GB/s HBM; device half of the streaming regime — "
            f"host refill excluded, tunnel measures {upload_mbps:.1f} MiB/s, "
            f"backend {jax.default_backend()})"
        ),
        "vs_baseline": round(hbm_gbps * 1e9 / HBM_ROOFLINE, 4),
        "bandwidth_util": round(hbm_gbps * 1e9 / HBM_ROOFLINE, 4),
    }


def bench_intersect_4krows() -> dict:
    """Gram-INELIGIBLE headline: 4096 distinct rows (>> 16x batch, so the
    all-pairs MXU shortcut can't precompute the answers) forces the
    gather path — the shape a real workload with thousands of distinct
    rows hits.  Uses the row-major pipelined kernel (one contiguous DMA
    descriptor per operand covering every slice): on v5e the DMA engine
    processes descriptors serially at ~1 us each, so achievable bandwidth
    is descriptor-size-bound.  Round-5 ceiling measurement at 4 slices:
    2 descriptors/query (the gather minimum — operand rows are random,
    so no descriptor can carry more than one row) x the measured
    ~1.3 us issue rate = 2.6 us/query = util ~0.49-0.51, which this
    kernel hits exactly; deeper pipelines (depth 4/8) and multi-query
    grid steps both measured SLOWER (VMEM pressure; issue stays serial).
    Past this rung the lane needs bigger rows, not more overlap: 16
    slices (2 MB descriptors) measures 0.64-0.76.  Reports HBM bandwidth
    utilization vs the v5e roofline (true traffic: two operand rows per
    query)."""
    n_slices = int(os.environ.get("BENCH_SLICES", "4"))
    n_rows = int(os.environ.get("BENCH_ROWS", "4096"))
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    iters = int(os.environ.get("BENCH_ITERS", "256"))

    import jax
    import jax.numpy as jnp
    from jax import lax

    from pilosa_tpu.ops.pallas_kernels import fused_gather_count2_rowmajor
    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE

    W = WORDS_PER_SLICE
    rng = np.random.default_rng(42)
    all_pairs = rng.integers(0, n_rows, size=(iters, batch, 2), dtype=np.int32)

    # Device-generated (uploading multi-GB through the tunnel measures the
    # tunnel; see the headline config) in row-major tiled form.
    @jax.jit
    def gen_matrix(key):
        return jax.random.bits(key, (n_rows, n_slices, W // 128, 128), jnp.uint32)

    drm = gen_matrix(jax.random.PRNGKey(42))
    dpairs = jax.device_put(all_pairs)

    interp = jax.default_backend() != "tpu"  # CPU smoke runs

    @jax.jit
    def run_stream(rm, pairs_stream):
        def step(carry, prs):
            return carry, fused_gather_count2_rowmajor("and", rm, prs, interpret=interp)

        out = lax.scan(step, 0, pairs_stream)[1]
        return out, out.astype(jnp.int64).sum()

    out_dev, _ = run_stream(drm, dpairs)  # warm + compile

    def timed():
        out_d, digest = run_stream(drm, dpairs)
        np.asarray(digest)
        return out_d

    dt, out_dev = _best_of_runs(timed)
    out = np.asarray(out_dev)
    qps = iters * batch / dt
    # Gather traffic: 2 rows x n_slices per query, W*4 bytes each.
    bytes_moved = iters * batch * 2 * n_slices * W * 4
    bw_util = bytes_moved / dt / HBM_ROOFLINE

    # Correctness gate: numpy ground truth for the first few queries from
    # a fetched row subset (fetching all operand rows would take minutes
    # through the tunnel).
    from pilosa_tpu.roaring import _POPCNT8

    n_gate = min(8, batch)
    gate_rows = sorted({int(r) for r in all_pairs[0, :n_gate].ravel()})
    pos = {r: i for i, r in enumerate(gate_rows)}
    host_rows = np.asarray(drm[np.array(gate_rows)]).reshape(len(gate_rows), n_slices, W)
    for k in range(n_gate):
        a = host_rows[pos[int(all_pairs[0, k, 0])]]
        b = host_rows[pos[int(all_pairs[0, k, 1])]]
        want = int(_POPCNT8[(a & b).view(np.uint8)].sum())
        assert out[0, k] == want, f"gate query {k}: {out[0, k]} != {want}"
    return {
        "metric": "intersect_count_4krows_qps",
        "value": round(qps, 1),
        "unit": (
            f"queries/sec, Gram-ineligible ({n_rows} rows x {n_slices} slices, "
            f"batch {batch}, row-major pipelined gather kernel, "
            f"backend {jax.default_backend()})"
        ),
        "vs_baseline": round(bw_util, 4),
        "bandwidth_util": round(bw_util, 4),
    }


def bench_topn_p50() -> dict:
    """TopN latency at a billion columns (BASELINE.json's 'TopN p50 @ 1B
    cols' metric): score EVERY row against a src bitmap over all slices
    (the candidate phase's device work, fragment.go:493-625 analog).
    Default 960 slices x 64 rows = ~1.01B columns, ~7.9 GiB resident on
    one chip, streamed per query through the fused Pallas scorer
    (fused_topn_counts: ~2 MB auto-pipelined blocks, per-row accumulator
    resident in VMEM).

    Queries are chained in one jitted scan and the reported latency is
    scan_time / n_q: per-dispatch timing through this environment's
    remote tunnel adds ~80-120 ms of round trip per query (the r02
    recording's 111 ms 'p50' was mostly that artifact) — a host-attached
    TPU dispatches in tens of microseconds.  Each step XORs src with a
    distinct mask so no two queries are the same computation."""
    n_slices = int(os.environ.get("BENCH_SLICES", "960"))
    n_rows = int(os.environ.get("BENCH_ROWS", "64"))
    n_q = int(os.environ.get("BENCH_ITERS", "64"))

    import jax
    import jax.numpy as jnp
    from jax import lax

    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE
    from pilosa_tpu.ops.pallas_kernels import fused_topn_counts

    W = WORDS_PER_SLICE
    rng = np.random.default_rng(42)
    masks = rng.integers(0, 1 << 32, size=(n_q,), dtype=np.uint32)

    # Device-generated (7.9 GB host-gen + upload took ~40 min of the r02
    # attempt's runtime through the tunnel).
    @jax.jit
    def gen(key):
        rows = jax.random.bits(
            key, (n_slices, n_rows, W // 128, 128), jnp.uint32
        )
        src = jax.random.bits(
            jax.random.fold_in(key, 1), (n_slices, W // 128, 128), jnp.uint32
        )
        return rows, src

    drows, dsrc = gen(jax.random.PRNGKey(42))

    interp = jax.default_backend() != "tpu"  # CPU smoke runs

    @jax.jit
    def run_stream(rws, s, ms):
        def step(carry, m):
            return carry, fused_topn_counts(rws, s ^ m, interpret=interp)

        out = lax.scan(step, 0, ms)[1]  # [n_q, n_rows]
        return out, out.astype(jnp.int64).sum()

    dmasks = jax.device_put(masks)
    out_dev, _ = run_stream(drows, dsrc, dmasks)  # warm + compile
    def timed():
        out_d, digest = run_stream(drows, dsrc, dmasks)
        np.asarray(digest)
        return out_d

    dt, out_dev = _best_of_runs(timed, default_runs=3)
    per_q = dt / n_q
    counts = np.asarray(out_dev)  # [n_q, n_rows] — small fetch

    # Host-side heap merge (the non-device half of TopN) — measured but
    # tiny next to the scan.
    t0 = time.perf_counter()
    top = sorted(zip(counts[0].tolist(), range(n_rows)), reverse=True)[:10]
    heap_dt = time.perf_counter() - t0
    assert top[0][0] > 0

    # Correctness gate: slice 0's counts for query 0 vs numpy.
    from pilosa_tpu.roaring import _POPCNT8

    r0 = np.asarray(drows[:1]).reshape(n_rows, W)
    s0 = np.asarray(dsrc[:1]).reshape(W) ^ masks[0]
    want = _POPCNT8[(r0 & s0).view(np.uint8)].reshape(n_rows, -1).sum(axis=1)
    got = np.asarray(
        fused_topn_counts(drows[:1], (dsrc[:1] ^ masks[0]), interpret=interp)
    )
    assert np.array_equal(got, want), "topn counts mismatch (slice 0)"

    bw_util = (n_slices * n_rows * W * 4 + n_slices * W * 4) / per_q / HBM_ROOFLINE
    return {
        "metric": "topn_p50_ms",
        "value": round((per_q + heap_dt) * 1e3, 2),
        "unit": (
            f"ms per TopN over {n_slices * (1 << 20) / 1e6:.0f}M columns "
            f"({n_rows} rows resident, scan-chained mean over {n_q} queries, "
            f"Pallas scorer, backend {jax.default_backend()})"
        ),
        "vs_baseline": round(bw_util, 4),
        "bandwidth_util": round(bw_util, 4),
    }


def _run_lockstep_job(queries, n_clients: int, n_ranks: int, env_extra=None,
                      warm: int = 6):
    """Spawn an n-rank lockstep job (tests/lockstep_worker.py), POST
    ``queries`` from ``n_clients`` concurrent clients, tear the job
    down, and return (wall_seconds, responses).  Shared by the lockstep
    throughput bench and the request-coalescing bench (which runs the
    SAME job twice with different coalescing env)."""
    import subprocess
    import sys
    import tempfile
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    repo = os.path.dirname(os.path.abspath(__file__))

    def free_port():
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    coord, control, http = free_port(), free_port(), free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = repo
    env["XLA_FLAGS"] = ""
    env.update(env_extra or {})
    worker = os.path.join(repo, "tests", "lockstep_worker.py")
    errs = [tempfile.NamedTemporaryFile("w+", delete=False) for _ in range(n_ranks)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, f"127.0.0.1:{coord}", str(n_ranks), str(pid),
             str(control), str(http)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=errs[pid],
            cwd=repo, env=env, text=True)
        for pid in range(n_ranks)
    ]
    try:
        line = procs[0].stdout.readline()
        assert json.loads(line).get("ready"), line

        def post(q):
            req = urllib.request.Request(
                f"http://127.0.0.1:{http}/index/g/query", data=q.encode(), method="POST")
            return json.loads(urllib.request.urlopen(req, timeout=120).read())

        for q in queries[:warm]:
            post(q)  # warm: matrices, jit, memo
        t0 = time.perf_counter()
        with ThreadPoolExecutor(n_clients) as pool:
            outs = list(pool.map(post, queries))
        dt = time.perf_counter() - t0
    finally:
        try:
            procs[0].stdin.write("\n")
            procs[0].stdin.flush()
        except Exception:
            pass
        stats = {}
        try:  # rank 0's final JSON line carries coalescing telemetry
            for line in procs[0].stdout:
                line = line.strip()
                if line:
                    stats = json.loads(line)
        except Exception:
            pass
        for p in procs:
            try:
                p.wait(timeout=60)
            except Exception:
                p.kill()
        for f in errs:
            f.close()
            os.unlink(f.name)
    return dt, outs, stats


def bench_lockstep() -> dict:
    """Lockstep-service throughput: a 2-rank SPMD job (CPU gloo mesh —
    the shape this box can spawn; on a pod the same path rides ICI)
    serving batched PQL over HTTP with concurrent clients, vs the SAME
    requests through a single in-process executor.  Exercises the
    pipelined total order: N requests in flight on the control plane,
    execution in sequence order on both ranks."""

    batch = int(os.environ.get("BENCH_BATCH", "64"))
    iters = int(os.environ.get("BENCH_ITERS", "60"))
    n_clients = int(os.environ.get("BENCH_THREADS", "6"))
    n_ranks = int(os.environ.get("BENCH_RANKS", "2"))

    rng = np.random.default_rng(17)

    def mk_query():
        pairs = rng.integers(0, 4, size=(batch, 2))
        return " ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for a, b in pairs
        )

    queries = [mk_query() for _ in range(iters)]
    dt, outs, _stats = _run_lockstep_job(queries, n_clients, n_ranks)
    qps = iters * batch / dt
    assert all("results" in o and len(o["results"]) == batch for o in outs)

    # Single-rank baseline: same queries through one in-process executor.
    import tempfile as _tf

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.pilosa import SLICE_WIDTH

    with _tf.TemporaryDirectory() as d:
        h = Holder(d)
        h.open()
        idx = h.create_index("g")
        idx.create_frame("f", FrameOptions(time_quantum="YM"))
        fr = idx.frame("f")
        for r in range(4):
            for s in range(max(4, 2 * n_ranks)):  # mirror the workers' seed
                fr.set_bit("standard", r, s * SLICE_WIDTH + 10 + r)
                fr.set_bit("standard", r, s * SLICE_WIDTH + 500)
        ex = Executor(h)
        for q in queries[:6]:
            ex.execute("g", q)
        t0 = time.perf_counter()
        for q in queries:
            ex.execute("g", q)
        base_dt = time.perf_counter() - t0
        h.close()
    base_qps = iters * batch / base_dt
    return {
        "metric": "lockstep_service_qps",
        "value": round(qps, 1),
        "unit": (
            f"PQL queries/sec via {n_ranks}-rank lockstep HTTP ({n_clients} clients, "
            f"batch {batch}, pipelined; single-rank in-process executor "
            f"{base_qps:,.0f} q/s on this host)"
        ),
        "vs_baseline": round(qps / base_qps, 3),
    }


def bench_lockstep_coalesce() -> dict:
    """Lockstep request-coalescing tier: SMALL single-call requests from
    many concurrent clients — the shape where the per-request fixed cost
    (HTTP + one control-plane entry + one ack round per request,
    BACKLOG's ~1.9 ms/request) dominates — with coalescing ON (rank 0
    drains its queue into one batch replay entry; the default) vs
    forced OFF (``PILOSA_TPU_LOCKSTEP_COALESCE=1``: one entry per
    request, the PR-1 behavior).  Per-request overhead must DROP with
    batch size; both phases run the same request stream on a fresh
    2-rank job.  BENCH_SMOKE=1 shrinks the stream for CI."""
    smoke = os.environ.get("BENCH_SMOKE", "").lower() in ("1", "true", "yes")
    iters = int(os.environ.get("BENCH_ITERS", "24" if smoke else "400"))
    n_clients = int(os.environ.get("BENCH_THREADS", "4" if smoke else "16"))
    n_ranks = int(os.environ.get("BENCH_RANKS", "2"))

    rng = np.random.default_rng(29)
    queries = [
        f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
        for a, b in rng.integers(0, 4, size=(iters, 2)).tolist()
    ]
    tiers = []
    for name, env_extra in (
        ("coalesce_on", {}),
        ("coalesce_off", {"PILOSA_TPU_LOCKSTEP_COALESCE": "1"}),
    ):
        dt, outs, stats = _run_lockstep_job(queries, n_clients, n_ranks, env_extra)
        assert all("results" in o and len(o["results"]) == 1 for o in outs)
        n_b = stats.get("batches") or 0
        tiers.append({
            "tier": name,
            "rps": round(iters / dt, 1),
            "per_request_ms": round(1e3 * dt / iters, 3),
            "batches": n_b,
            "mean_batch": (
                round(stats.get("requests", 0) / n_b, 2) if n_b else None
            ),
        })
    on, off = tiers[0], tiers[1]
    return {
        "metric": "lockstep_coalesce_rps",
        "value": on["rps"],
        "unit": (
            f"single-call PQL requests/sec via {n_ranks}-rank lockstep HTTP "
            f"({n_clients} clients; coalesced {on['per_request_ms']} ms/req vs "
            f"uncoalesced {off['per_request_ms']} ms/req)"
        ),
        "vs_baseline": round(on["rps"] / off["rps"], 3),
        "tiers": tiers,
    }


def bench_overload() -> dict:
    """Request-lifecycle QoS tier: a REAL HTTP server (numpy engine)
    driven past saturation by closed-loop clients, with the QoS door ON
    (bounded per-class admission + per-request deadlines; overflow
    sheds 429 + Retry-After at the door) vs OFF (unbounded admission,
    no deadline — the pre-QoS behavior).

    Three phases: ``presat`` measures the pre-saturation peak (clients
    == read depth), then the overload phases run 2x the door capacity
    (depth admitted + depth waiting).  Non-collapse contract: with QoS
    on the shed rate is > 0, the SERVED requests' p99 stays near the
    pre-saturation p99, and goodput stays within ~20% of peak; with QoS
    off every request is admitted and the served p99 degrades with the
    queue depth instead.  BENCH_SMOKE=1 shrinks the shapes for CI."""
    import tempfile
    import urllib.error
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    from pilosa_tpu.config import Config
    from pilosa_tpu.server.server import Server

    smoke = os.environ.get("BENCH_SMOKE", "").lower() in ("1", "true", "yes")
    depth = int(os.environ.get("BENCH_QOS_DEPTH", "2" if smoke else "4"))
    # 2x the DOOR capacity (depth active + depth waiting) = 4x depth.
    overload_clients = int(os.environ.get("BENCH_THREADS", str(4 * depth)))
    phase_s = float(os.environ.get("BENCH_OVERLOAD_SECS", "1.5" if smoke else "8"))
    deadline_ms = float(os.environ.get("BENCH_DEADLINE_MS", "500" if smoke else "2000"))
    n_slices = int(os.environ.get("BENCH_SLICES", "2" if smoke else "4"))
    n_rows = int(os.environ.get("BENCH_ROWS", "8" if smoke else "16"))
    batch = int(os.environ.get("BENCH_BATCH", "8" if smoke else "32"))

    from pilosa_tpu.pilosa import SLICE_WIDTH

    rng = np.random.default_rng(31)
    queries = []
    for seed in range(8):
        prs = np.random.default_rng(seed).integers(0, n_rows, size=(batch, 2))
        queries.append(" ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for a, b in prs.tolist()
        ))

    def mk_server(d, qos_on: bool) -> Server:
        # qcache OFF on both sides: this tier measures the ADMISSION
        # door under real execution load — with the query result cache
        # on, the repeated-query mix is served from memory and the door
        # never saturates (that regime is BENCH_CONFIG=qcache's job).
        cfg = Config(data_dir=d, host="127.0.0.1:0", engine="numpy", stats="expvar",
                     qcache_enabled=False)
        if qos_on:
            cfg.qos_read_depth = depth
            cfg.qos_write_depth = depth
            cfg.qos_queue_wait_ms = 25.0
            cfg.qos_retry_after_ms = 50.0
            cfg.default_deadline_ms = deadline_ms
        else:
            cfg.qos_read_depth = cfg.qos_write_depth = cfg.qos_admin_depth = 0
            cfg.default_deadline_ms = 0.0
        srv = Server(cfg)
        srv.open()
        idx = srv.holder.create_index("o")
        from pilosa_tpu.core.frame import FrameOptions

        idx.create_frame("f", FrameOptions())
        fr = idx.frame("f")
        rows = np.repeat(np.arange(n_rows, dtype=np.uint64), 2000)
        for s in range(n_slices):
            cols = rng.integers(0, SLICE_WIDTH, size=len(rows)).astype(
                np.uint64
            ) + np.uint64(s * SLICE_WIDTH)
            fr.import_bits(rows, cols)
        return srv

    def run_phase(host: str, n_clients: int, dur_s: float) -> dict:
        """Closed-loop load: each client posts back-to-back until the
        phase ends; sheds honor the server's Retry-After."""
        t_end = time.perf_counter() + dur_s

        def client(i: int) -> dict:
            lat: list = []
            out = {"served": 0, "shed": 0, "expired": 0, "timeouts": 0, "errors": 0}
            k = i
            while time.perf_counter() < t_end:
                q = queries[k % len(queries)]
                k += 1
                req = urllib.request.Request(
                    f"http://{host}/index/o/query", data=q.encode(), method="POST")
                t1 = time.perf_counter()
                try:
                    with urllib.request.urlopen(req, timeout=30) as resp:
                        resp.read()
                    lat.append(time.perf_counter() - t1)
                    out["served"] += 1
                except urllib.error.HTTPError as e:
                    e.read()
                    if e.code == 429 or e.code == 503:
                        out["shed"] += 1
                        try:
                            wait = float(e.headers.get("Retry-After", "0.05"))
                        except (TypeError, ValueError):
                            wait = 0.05
                        time.sleep(min(wait, 0.25))
                    elif e.code == 504:
                        out["expired"] += 1
                    else:
                        out["errors"] += 1
                except OSError:
                    out["timeouts"] += 1
            out["lat"] = lat
            return out

        t0 = time.perf_counter()
        with ThreadPoolExecutor(n_clients) as pool:
            outs = list(pool.map(client, range(n_clients)))
        dt = time.perf_counter() - t0
        lat = sorted(x for o in outs for x in o["lat"])
        total = {k: sum(o[k] for o in outs)
                 for k in ("served", "shed", "expired", "timeouts", "errors")}
        offered = sum(total.values())
        return {
            "goodput_qps": round(total["served"] / dt, 1),
            "p50_ms": round(1e3 * lat[len(lat) // 2], 2) if lat else None,
            "p99_ms": (
                round(1e3 * lat[min(len(lat) - 1, int(len(lat) * 0.99))], 2)
                if lat else None
            ),
            "shed_rate": round(total["shed"] / offered, 3) if offered else 0.0,
            **total,
        }

    tiers = []
    with tempfile.TemporaryDirectory() as d:
        srv = mk_server(d, qos_on=True)
        try:
            for q in queries:  # warm: matrices + serve lane
                run = urllib.request.Request(
                    f"http://{srv.host}/index/o/query", data=q.encode(), method="POST")
                urllib.request.urlopen(run, timeout=60).read()
            presat = run_phase(srv.host, depth, phase_s)
            tiers.append({"tier": "presat", "clients": depth, **presat})
            on = run_phase(srv.host, overload_clients, phase_s)
            tiers.append({"tier": "overload_qos_on", "clients": overload_clients, **on})
        finally:
            srv.close()
    with tempfile.TemporaryDirectory() as d:
        srv = mk_server(d, qos_on=False)
        try:
            for q in queries:
                run = urllib.request.Request(
                    f"http://{srv.host}/index/o/query", data=q.encode(), method="POST")
                urllib.request.urlopen(run, timeout=60).read()
            off = run_phase(srv.host, overload_clients, phase_s)
            tiers.append({"tier": "overload_qos_off", "clients": overload_clients, **off})
        finally:
            srv.close()

    on["goodput_vs_peak"] = round(
        on["goodput_qps"] / presat["goodput_qps"], 3
    ) if presat["goodput_qps"] else None
    tiers[1]["goodput_vs_peak"] = on["goodput_vs_peak"]
    p99_ratio = (
        round(off["p99_ms"] / on["p99_ms"], 2)
        if on.get("p99_ms") and off.get("p99_ms") else None
    )
    return {
        "metric": "overload_goodput_qps",
        "value": on["goodput_qps"],
        "unit": (
            f"served requests/sec at 2x door capacity ({overload_clients} clients, "
            f"read depth {depth}; shed rate {on['shed_rate']}, served p99 "
            f"{on['p99_ms']} ms vs presat {presat['p99_ms']} ms; QoS-off p99 "
            f"{off['p99_ms']} ms = {p99_ratio}x worse)"
        ),
        "vs_baseline": p99_ratio,
        "tiers": tiers,
    }


def bench_tenancy() -> dict:
    """Multi-tenant hostile-neighbor tier: a REAL HTTP server with the
    [tenancy] fair-share door ON, a weighted POLITE tenant (the paying
    interactive workload, weight 3) sharing the read door with a
    HOSTILE tenant flooding at >= 2x the door's capacity (2x depth
    closed-loop clients).  Tenants are named by X-Pilosa-Tenant
    headers — the same resolution seam the handler, lockstep front end,
    and replica router share.

    Three phases: ``polite_baseline`` measures the polite tenant's
    ISOLATED p99 (same client count, empty door); ``hostile_flood_on``
    adds the flood with isolation ON and asserts IN-RUN that (a) the
    polite tenant's p99 stays within 1.5x its isolated baseline, (b)
    the polite tenant sheds NOTHING (its share of the wait lane is
    reserved — the flooder can never fill the door against it), and
    (c) the hostile tenant really sheds (the flood was real);
    ``hostile_flood_off`` repeats the flood with tenancy disabled and
    records the polite tenant's degraded p99/sheds for the A/B.
    BENCH_SMOKE=1 shrinks the shapes for CI."""
    import tempfile
    import urllib.error
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    from pilosa_tpu.config import Config
    from pilosa_tpu.server.server import Server

    smoke = os.environ.get("BENCH_SMOKE", "").lower() in ("1", "true", "yes")
    # Depth stays 8 even under BENCH_SMOKE: the weighted share split
    # needs a door deep enough that the hostile tenant's GUARANTEED
    # floor (cap never rounds below 1 — presence always buys progress)
    # is a small fraction of the polite tenant's share.  The requests
    # are execution-bound, so even perfect door isolation concedes the
    # floor's slot of CPU to the flooder: with polite at 7/8 of the
    # door the concession is ~1/7th, well inside the 1.5x gate; at
    # depth 2 both tenants round to cap 1 and the gate measures a
    # 50/50 CPU split, not isolation.
    depth = int(os.environ.get("BENCH_QOS_DEPTH", "8"))
    # The polite tenant runs at its fair share of the door (weight 7 of
    # 8 total); the hostile flood offers >= 2x the DOOR capacity (2x
    # depth of closed-loop clients hammering a depth-deep door).
    polite_clients = max(1, (7 * depth) // 8)
    hostile_clients = int(os.environ.get("BENCH_THREADS", str(2 * depth)))
    phase_s = float(os.environ.get("BENCH_TENANCY_SECS", "2.5" if smoke else "8"))
    n_slices = int(os.environ.get("BENCH_SLICES", "2" if smoke else "4"))
    n_rows = int(os.environ.get("BENCH_ROWS", "8" if smoke else "16"))
    batch = int(os.environ.get("BENCH_BATCH", "8" if smoke else "32"))

    from pilosa_tpu.pilosa import SLICE_WIDTH

    rng = np.random.default_rng(47)
    queries = []
    for seed in range(8):
        prs = np.random.default_rng(seed).integers(0, n_rows, size=(batch, 2))
        queries.append(" ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for a, b in prs.tolist()
        ))

    def mk_server(d, tenancy_on: bool) -> Server:
        # qcache OFF (the door must saturate on real execution, same as
        # the overload tier); QoS door ON in BOTH legs — the A/B
        # isolates what fair-share adds over plain bounded admission.
        cfg = Config(data_dir=d, host="127.0.0.1:0", engine="numpy",
                     stats="expvar", qcache_enabled=False)
        cfg.qos_read_depth = depth
        cfg.qos_write_depth = depth
        # Generous wait lane: the polite tenant's isolation shows up as
        # BOUNDED waiting, never as sheds — its reserved share of the
        # lane admits within a service time.
        cfg.qos_queue_wait_ms = 2000.0
        # Standard Retry-After: shed hostile clients genuinely back off.
        # A tiny hint here would turn the flood into a doorknock storm
        # whose admission-path CPU (connect/parse/classify/shed) is
        # itself the interference — the door can only isolate work it
        # gets to arbitrate.
        cfg.qos_retry_after_ms = 250.0
        if tenancy_on:
            cfg.tenancy_enabled = True
            cfg.tenancy_weights = "polite=7,hostile=1"
        srv = Server(cfg)
        srv.open()
        idx = srv.holder.create_index("t")
        from pilosa_tpu.core.frame import FrameOptions

        idx.create_frame("f", FrameOptions())
        fr = idx.frame("f")
        rows = np.repeat(np.arange(n_rows, dtype=np.uint64), 2000)
        for s in range(n_slices):
            cols = rng.integers(0, SLICE_WIDTH, size=len(rows)).astype(
                np.uint64
            ) + np.uint64(s * SLICE_WIDTH)
            fr.import_bits(rows, cols)
        return srv

    def run_phase(host: str, groups: dict, dur_s: float) -> dict:
        """Closed-loop per-tenant load: ``groups`` maps tenant name ->
        client count; every client stamps its tenant's header and
        honors Retry-After on sheds.  Returns per-tenant summaries."""
        t_end = time.perf_counter() + dur_s
        plan = [t for t, n in groups.items() for _ in range(n)]

        def client(i: int) -> dict:
            tenant = plan[i]
            lat: list = []
            out = {"tenant": tenant, "served": 0, "shed": 0, "errors": 0}
            k = i
            while time.perf_counter() < t_end:
                q = queries[k % len(queries)]
                k += 1
                req = urllib.request.Request(
                    f"http://{host}/index/t/query", data=q.encode(),
                    method="POST", headers={"X-Pilosa-Tenant": tenant})
                t1 = time.perf_counter()
                try:
                    with urllib.request.urlopen(req, timeout=30) as resp:
                        resp.read()
                    lat.append(time.perf_counter() - t1)
                    out["served"] += 1
                except urllib.error.HTTPError as e:
                    e.read()
                    if e.code in (429, 503):
                        out["shed"] += 1
                        try:
                            wait = float(e.headers.get("Retry-After", "0.05"))
                        except (TypeError, ValueError):
                            wait = 0.05
                        time.sleep(min(wait, 0.5))
                    else:
                        out["errors"] += 1
                except OSError:
                    out["errors"] += 1
            out["lat"] = lat
            return out

        t0 = time.perf_counter()
        with ThreadPoolExecutor(len(plan)) as pool:
            outs = list(pool.map(client, range(len(plan))))
        dt = time.perf_counter() - t0
        per: dict = {}
        for tenant in groups:
            mine = [o for o in outs if o["tenant"] == tenant]
            lat = sorted(x for o in mine for x in o["lat"])
            per[tenant] = {
                "clients": groups[tenant],
                "served": sum(o["served"] for o in mine),
                "shed": sum(o["shed"] for o in mine),
                "errors": sum(o["errors"] for o in mine),
                "goodput_qps": round(sum(o["served"] for o in mine) / dt, 1),
                "p50_ms": round(1e3 * lat[len(lat) // 2], 2) if lat else None,
                "p99_ms": (
                    round(1e3 * lat[min(len(lat) - 1, int(len(lat) * 0.99))], 2)
                    if lat else None
                ),
            }
        return per

    flood = {"polite": polite_clients, "hostile": hostile_clients}
    tiers = []
    with tempfile.TemporaryDirectory() as d:
        srv = mk_server(d, tenancy_on=True)
        try:
            for q in queries:  # warm: matrices + serve lane
                run = urllib.request.Request(
                    f"http://{srv.host}/index/t/query", data=q.encode(), method="POST")
                urllib.request.urlopen(run, timeout=60).read()
            base = run_phase(srv.host, {"polite": polite_clients}, phase_s)
            tiers.append({"tier": "polite_baseline", **base["polite"]})
            on = run_phase(srv.host, flood, phase_s)
            # Server-side per-tenant view under the flood (the
            # /debug/tenants satellite, scraped while the ledger is hot).
            dbg = json.loads(urllib.request.urlopen(
                f"http://{srv.host}/debug/tenants", timeout=30).read())
            tiers.append({"tier": "hostile_flood_on",
                          "polite": on["polite"], "hostile": on["hostile"],
                          "door": {
                              t: {k: row[k] for k in ("weight", "debt",
                                                      "admitted", "shed")}
                              for t, row in dbg.get("tenants", {}).items()
                          }})
        finally:
            srv.close()
    with tempfile.TemporaryDirectory() as d:
        srv = mk_server(d, tenancy_on=False)
        try:
            for q in queries:
                run = urllib.request.Request(
                    f"http://{srv.host}/index/t/query", data=q.encode(), method="POST")
                urllib.request.urlopen(run, timeout=60).read()
            off = run_phase(srv.host, flood, phase_s)
            tiers.append({"tier": "hostile_flood_off",
                          "polite": off["polite"], "hostile": off["hostile"]})
        finally:
            srv.close()

    # -- the hostile-neighbor gate (asserted IN-RUN: a violated
    # isolation contract exits nonzero, it doesn't just record) --------
    base_p99 = base["polite"]["p99_ms"]
    on_p99 = on["polite"]["p99_ms"]
    assert base_p99 and on_p99, (base, on)
    p99_vs_base = round(on_p99 / base_p99, 2)
    assert on_p99 <= 1.5 * base_p99, (
        f"isolation failed: polite p99 {on_p99} ms > 1.5x isolated "
        f"baseline {base_p99} ms under hostile flood"
    )
    assert on["polite"]["shed"] == 0, (
        f"isolation failed: polite tenant shed {on['polite']['shed']} "
        f"requests (its wait-lane share is reserved)"
    )
    assert on["hostile"]["shed"] > 0, (
        "flood never saturated the door: hostile tenant shed nothing "
        f"({hostile_clients} clients, depth {depth})"
    )
    off_p99 = off["polite"]["p99_ms"]
    off_ratio = (
        round(off_p99 / base_p99, 2) if off_p99 and base_p99 else None
    )
    return {
        "metric": "tenancy_polite_p99_ms",
        "value": on_p99,
        "unit": (
            f"polite tenant p99 under a {hostile_clients}-client hostile "
            f"flood (read depth {depth}, weights polite=7 hostile=1; "
            f"{p99_vs_base}x its isolated baseline {base_p99} ms, "
            f"0 polite sheds, {on['hostile']['shed']} hostile sheds; "
            f"tenancy OFF the same flood pushes polite p99 to "
            f"{off_p99} ms = {off_ratio}x baseline)"
        ),
        "vs_baseline": p99_vs_base,
        "tiers": tiers,
    }


def bench_replica() -> dict:
    """Replicated serving groups tier: N group SUBPROCESSES (each a full
    Server with its own holder and GIL — the dev-rig analog of one
    lockstep job per group) behind the ReplicaRouter, read QPS measured
    at 1 vs 2+ groups plus a router-off direct baseline:

    - ``direct_1g``: clients hit group 0's front door directly (no
      router) — the per-group ceiling and the router-overhead baseline;
    - ``router_1g``: the router over ONE group — isolates router cost;
    - ``router_Ng``: the router over all N groups — read throughput
      must SCALE with group count (``scaling_1_to_2`` is the headline
      ratio; acceptance >= 1.6x on the bench host).

    In-run invariants (fields in the router_Ng tier, asserted here):
    cross-group read-your-writes (a write acked by the router is
    visible on a direct read of EVERY group, and immediate router reads
    agree whichever group serves) and failover (killing one group's
    process leaves reads serving from the survivors while writes refuse
    503 until the set is quorate).  Groups are separate PROCESSES, so
    the scaling headline needs physical cores (>= n_groups + 1); a
    1-cpu box records ~1.0 by construction (the ``cpus`` field says
    which regime a line measured).  BENCH_SMOKE=1 shrinks the shapes
    for CI."""
    import subprocess
    import sys
    import tempfile
    import urllib.error
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    from pilosa_tpu.server.client import Client

    smoke = os.environ.get("BENCH_SMOKE", "").lower() in ("1", "true", "yes")
    n_groups = int(os.environ.get("BENCH_GROUPS", "2"))
    n_clients = int(os.environ.get("BENCH_THREADS", "4" if smoke else "16"))
    phase_s = float(os.environ.get("BENCH_REPLICA_SECS", "1.2" if smoke else "8"))
    n_slices = int(os.environ.get("BENCH_SLICES", "2" if smoke else "4"))
    n_rows = int(os.environ.get("BENCH_ROWS", "8" if smoke else "16"))
    batch = int(os.environ.get("BENCH_BATCH", "8" if smoke else "32"))
    bits_per_row = int(os.environ.get("BENCH_BITS_PER_ROW", "500" if smoke else "20000"))

    from pilosa_tpu.pilosa import SLICE_WIDTH

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "replica_group_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # numpy engine; jax must not probe TPUs
    env["PYTHONPATH"] = repo
    env.pop("PILOSA_TPU_QCACHE", None)  # measure execution, not cache hits

    queries = []
    for seed in range(8):
        prs = np.random.default_rng(seed).integers(0, n_rows, size=(batch, 2))
        queries.append(" ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for a, b in prs.tolist()
        ))

    def read_phase(host: str, dur_s: float) -> dict:
        """Closed-loop read load: each client posts back-to-back."""
        t_end = time.perf_counter() + dur_s

        def client(i: int) -> tuple[int, int]:
            served = errors = 0
            k = i
            while time.perf_counter() < t_end:
                q = queries[k % len(queries)]
                k += 1
                req = urllib.request.Request(
                    f"http://{host}/index/r/query", data=q.encode(), method="POST")
                try:
                    with urllib.request.urlopen(req, timeout=60) as resp:
                        resp.read()
                    served += 1
                except (urllib.error.URLError, OSError):
                    errors += 1
            return served, errors

        t0 = time.perf_counter()
        with ThreadPoolExecutor(n_clients) as pool:
            outs = list(pool.map(client, range(n_clients)))
        dt = time.perf_counter() - t0
        served = sum(s for s, _ in outs)
        errors = sum(e for _, e in outs)
        assert errors == 0, f"read phase saw {errors} transport errors"
        return {"read_qps": round(served / dt, 1), "served": served,
                "clients": n_clients}

    def free_port():
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    errs = [
        tempfile.NamedTemporaryFile("w+", delete=False) for _ in range(n_groups + 2)
    ]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, f"g{i}"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=errs[i],
            cwd=repo, env=env, text=True)
        for i in range(n_groups)
    ]
    tiers = []
    try:
        hosts = []
        for p in procs[:n_groups]:
            line = json.loads(p.stdout.readline())
            assert line.get("ready"), line
            hosts.append(line["host"])

        # ROUTERS run as their own processes (the production shape —
        # `pilosa-tpu replica-router`): the bench process only runs the
        # closed-loop clients, so the measured scaling is group-side,
        # not the bench's own GIL.
        def spawn_router(group_hosts, errfile):
            port = free_port()
            p = subprocess.Popen(
                [sys.executable, "-m", "pilosa_tpu", "replica-router",
                 "--groups", ",".join(
                     f"g{i}={h}" for i, h in enumerate(group_hosts)),
                 "--port", str(port)],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=errfile,
                cwd=repo, env=env, text=True)
            line = p.stdout.readline()
            assert "replica-router" in line, line
            return p, port

        router_all, all_port = spawn_router(hosts, errs[n_groups])
        procs.append(router_all)
        router_one, one_port = spawn_router(hosts[:1], errs[n_groups + 1])
        procs.append(router_one)

        # Seed THROUGH the router: schema + import fan to every group
        # (the write path under test is also the loader).
        rc = Client(f"127.0.0.1:{all_port}")
        rc.create_index("r")
        rc.create_frame("r", "f")
        rng = np.random.default_rng(41)
        bits = []
        for r in range(n_rows):
            for s in range(n_slices):
                cols = rng.integers(0, SLICE_WIDTH - 4096, size=bits_per_row)
                bits.extend((r, int(c) + s * SLICE_WIDTH) for c in cols)
        rc.import_bits("r", "f", bits)

        def direct(host, q):
            req = urllib.request.Request(
                f"http://{host}/index/r/query", data=q.encode(), method="POST")
            with urllib.request.urlopen(req, timeout=60) as resp:
                return json.loads(resp.read())["results"]

        for h in hosts:  # warm every group's serve lane
            for q in queries:
                direct(h, q)

        tiers.append({"tier": "direct_1g", "groups": 1,
                      **read_phase(hosts[0], phase_s)})
        tiers.append({"tier": "router_1g", "groups": 1,
                      **read_phase(f"127.0.0.1:{one_port}", phase_s)})

        # Cross-group read-your-writes, proven through the full-set
        # router BEFORE its throughput phase: the acked write is on
        # every group, and immediate router reads agree.
        probe_q = 'Count(Bitmap(rowID=0, frame="f"))'
        base = direct(hosts[0], probe_q)[0]
        rc.execute_query("r", f'SetBit(rowID=0, frame="f", columnID={SLICE_WIDTH - 1})')
        rw_ok = all(direct(h, probe_q) == [base + 1] for h in hosts)
        for _ in range(2 * n_groups):  # router reads spread over groups
            rw_ok = rw_ok and (
                direct(f"127.0.0.1:{all_port}", probe_q) == [base + 1]
            )
        assert rw_ok, "cross-group read-your-writes violated"

        tiers.append({"tier": f"router_{n_groups}g", "groups": n_groups,
                      **read_phase(f"127.0.0.1:{all_port}", phase_s)})

        # Failover: kill the LAST group's process; reads keep serving
        # from the survivors, writes refuse 503 until quorate.
        procs[n_groups - 1].kill()
        ok_reads = 0
        for _ in range(10):
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{all_port}/index/r/query",
                    data=probe_q.encode(), method="POST")
                with urllib.request.urlopen(req, timeout=30) as resp:
                    resp.read()
                ok_reads += 1
            except (urllib.error.URLError, OSError):
                pass  # at most the probe that trips the health mark
        write_503 = False
        try:
            rc.execute_query("r", 'SetBit(rowID=0, frame="f", columnID=7)')
        except Exception as e:  # noqa: BLE001 — ClientError carries .status
            write_503 = getattr(e, "status", None) == 503
        failover_ok = ok_reads >= 8 and write_503
        assert failover_ok, (ok_reads, write_503)
        # Router observability over HTTP (the router runs out-of-process).
        with urllib.request.urlopen(
            f"http://127.0.0.1:{all_port}/debug/vars", timeout=10
        ) as resp:
            snap = json.loads(resp.read())
        tiers[-1]["rw_ok"] = rw_ok
        tiers[-1]["failover_ok"] = failover_ok
        tiers[-1]["failovers"] = snap.get("replica.failover", 0)
        tiers[-1]["write_fanout"] = snap.get("replica.write_fanout", 0)
    finally:
        for p in procs[n_groups:]:  # router processes: no stdin protocol
            try:
                p.terminate()
            except Exception:  # noqa: BLE001
                pass
        for p in procs[:n_groups]:
            try:
                p.stdin.write("\n")
                p.stdin.flush()
            except Exception:  # noqa: BLE001 — already dead
                pass
        for p in procs:
            try:
                p.wait(timeout=30)
            except Exception:  # noqa: BLE001
                p.kill()
        for f in errs:
            f.close()
            os.unlink(f.name)

    by = {t["tier"]: t for t in tiers}
    qps_1 = by["router_1g"]["read_qps"]
    qps_n = by[f"router_{n_groups}g"]["read_qps"]
    scaling = round(qps_n / qps_1, 3) if qps_1 else None
    router_overhead = (
        round(by["direct_1g"]["read_qps"] / qps_1, 3) if qps_1 else None
    )
    return {
        "metric": "replica_read_qps",
        "value": qps_n,
        "unit": (
            f"read requests/sec via the replica router over {n_groups} groups "
            f"({n_clients} clients, batch {batch}; 1-group router {qps_1} q/s "
            f"= x{scaling} scaling on {os.cpu_count()} cpus, direct/router "
            f"overhead x{router_overhead}; rw + failover asserted in-run)"
        ),
        "vs_baseline": scaling,
        "scaling_1_to_2": scaling,
        "router_overhead": router_overhead,
        # Group processes scale with PHYSICAL cores: scaling toward
        # n_groups needs cpus >= n_groups + 1 (router + clients ride the
        # remainder); a 1-cpu CI box records ~1.0 by construction.
        "cpus": os.cpu_count(),
        "tiers": tiers,
    }


def bench_recovery() -> dict:
    """Durable-write-log recovery tier: write availability through the
    replica router when a group dies, and convergence time when it
    comes back.  3 group SUBPROCESSES (pinned data dirs, so a restart
    resumes from disk) behind an out-of-process CLI router running a
    DURABLE WAL:

    - ``writes_3g``: sequential write throughput with the full group
      set (the fixed-cost baseline: WAL append + 3-way fan-out);
    - ``writes_2g``: the LAST group is SIGKILLed mid-stream and the
      writes keep flowing on the degraded quorum — the tier asserts
      ZERO failed writes in this phase (the old full-set rule 503'd
      every one of them);
    - ``catchup``: the killed group restarts (same data dir, bumped
      epoch), the router replays the missed WAL suffix, and the tier
      measures time-to-rejoin plus asserts CONVERGENCE (identical
      query results on every group) and that reads route to the
      rejoined group again.

    ``BENCH_RECOVERY_WRITES`` sizes each write phase; ``BENCH_SMOKE=1``
    shrinks for CI."""
    import shutil
    import subprocess
    import sys
    import tempfile
    import urllib.error
    import urllib.request

    from pilosa_tpu.server.client import Client

    smoke = os.environ.get("BENCH_SMOKE", "").lower() in ("1", "true", "yes")
    n_writes = int(os.environ.get("BENCH_RECOVERY_WRITES", "60" if smoke else "600"))
    batch = int(os.environ.get("BENCH_BATCH", "8"))

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "replica_group_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    env.pop("PILOSA_TPU_QCACHE", None)

    def free_port():
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    root = tempfile.mkdtemp(prefix="pilosa_recovery_")
    errs = [open(os.path.join(root, f"err{i}.log"), "w+") for i in range(4)]
    # FIXED front-door ports: a restarted group must come back at the
    # same address the router holds.
    group_ports = [free_port() for _ in range(3)]

    def spawn_group(i: int, epoch: int):
        genv = dict(env)
        genv["PILOSA_WORKER_DATA_DIR"] = os.path.join(root, f"g{i}")
        genv["PILOSA_WORKER_HOST"] = f"127.0.0.1:{group_ports[i]}"
        p = subprocess.Popen(
            [sys.executable, worker, f"g{i}@{epoch}"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=errs[i],
            cwd=repo, env=genv, text=True)
        line = json.loads(p.stdout.readline())
        assert line.get("ready"), line
        return p, line["host"]

    procs = []
    tiers = []
    try:
        groups = [spawn_group(i, 1) for i in range(3)]
        procs = [p for p, _ in groups]
        hosts = [h for _, h in groups]

        router_port = free_port()
        router = subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu", "replica-router",
             "--groups", ",".join(f"g{i}={h}" for i, h in enumerate(hosts)),
             "--port", str(router_port),
             "--wal-dir", os.path.join(root, "wal"),
             "--probe-interval", "0.1"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=errs[3], cwd=repo, env=env, text=True)
        procs.append(router)
        line = router.stdout.readline()
        assert "replica-router" in line, line

        rc = Client(f"127.0.0.1:{router_port}", timeout=60)
        rc.create_index("r")
        rc.create_frame("r", "f")

        def write_phase(start: int, n: int) -> dict:
            """Sequential batched writes; every one must COMMIT."""
            failed = 0
            t0 = time.perf_counter()
            for k in range(start, start + n, batch):
                q = " ".join(
                    f'SetBit(rowID=1, frame="f", columnID={c})'
                    for c in range(k, min(k + batch, start + n))
                )
                try:
                    rc.execute_query("r", q)
                except Exception:  # noqa: BLE001 — ClientError carries status
                    failed += 1
            dt = time.perf_counter() - t0
            return {
                "write_qps": round(n / dt, 1),
                "writes": n,
                "failed_batches": failed,
                "batch": batch,
            }

        def rstatus() -> dict:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{router_port}/replica/status", timeout=10
            ) as resp:
                return json.loads(resp.read())

        def direct_count(host: str) -> int:
            req = urllib.request.Request(
                f"http://{host}/index/r/query",
                data=b'Count(Bitmap(rowID=1, frame="f"))', method="POST")
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read())["results"][0]

        tiers.append({"tier": "writes_3g", "groups": 3, **write_phase(0, n_writes)})
        assert tiers[-1]["failed_batches"] == 0, tiers[-1]

        # Kill the LAST group hard, mid-stream: writes must KEEP
        # COMMITTING on the degraded quorum — the headline behavior the
        # WAL buys (the old full-set rule turned this into a 503 storm).
        procs[2].kill()
        tiers.append({
            "tier": "writes_2g", "groups": 2,
            **write_phase(n_writes, n_writes),
        })
        no_storm = tiers[-1]["failed_batches"] == 0
        assert no_storm, tiers[-1]
        assert direct_count(hosts[0]) == direct_count(hosts[1]) == 2 * n_writes

        # Restart the dead group (same data dir, bumped epoch) and time
        # catch-up: restart -> probe -> WAL suffix replay -> rejoin.
        t_restart = time.perf_counter()
        p2, h2 = spawn_group(2, 2)
        procs[2] = p2
        hosts[2] = h2
        catchup_s = None
        deadline = time.monotonic() + (60 if smoke else 300)
        while time.monotonic() < deadline:
            g2 = next(g for g in rstatus()["groups"] if g["name"] == "g2")
            if g2["healthy"] and g2["caughtUp"]:
                catchup_s = round(time.perf_counter() - t_restart, 3)
                break
            time.sleep(0.05)
        assert catchup_s is not None, "g2 never rejoined"
        converged = (
            direct_count(hosts[2]) == direct_count(hosts[0]) == 2 * n_writes
        )
        assert converged
        # Reads route to the rejoined group again.
        served = set()
        for _ in range(12):
            req = urllib.request.Request(
                f"http://127.0.0.1:{router_port}/index/r/query",
                data=b'Count(Bitmap(rowID=1, frame="f"))', method="POST")
            with urllib.request.urlopen(req, timeout=30) as resp:
                resp.read()
                served.add((resp.headers.get("X-Pilosa-Group") or "").split("@")[0])
        rejoined_reads = "g2" in served
        with urllib.request.urlopen(
            f"http://127.0.0.1:{router_port}/debug/vars", timeout=10
        ) as resp:
            snap = json.loads(resp.read())
        tiers.append({
            "tier": "catchup",
            "catchup_s": catchup_s,
            "replayed": snap.get("replica.replayed", 0),
            "lag_at_restart": n_writes // batch + (1 if n_writes % batch else 0),
            "converged": converged,
            "rejoined_reads": rejoined_reads,
            "wal": rstatus()["wal"],
        })
    finally:
        for p in procs:
            try:
                p.kill()
            except Exception:  # noqa: BLE001
                pass
        for p in procs:
            try:
                p.wait(timeout=30)
            except Exception:  # noqa: BLE001
                pass
        for f in errs:
            f.close()
        shutil.rmtree(root, ignore_errors=True)

    by = {t["tier"]: t for t in tiers}
    qps3, qps2 = by["writes_3g"]["write_qps"], by["writes_2g"]["write_qps"]
    return {
        "metric": "recovery_write_qps",
        "value": qps2,
        "unit": (
            f"committed writes/sec on the DEGRADED quorum (2/3 groups, batch "
            f"{batch}; full set {qps3} w/s; zero failed writes with a group "
            f"down; catch-up replayed the {by['catchup']['replayed']}-record "
            f"WAL suffix in {by['catchup']['catchup_s']} s and the group "
            f"rejoined reads converged)"
        ),
        "vs_baseline": round(qps2 / qps3, 3) if qps3 else None,
        "catchup_s": by["catchup"]["catchup_s"],
        "cpus": os.cpu_count(),
        "tiers": tiers,
    }


def bench_resync() -> dict:
    """Automated-resync tier: a BLANK group joins a loaded 2-group
    cluster and self-heals with zero operator action.  3 group
    subprocesses behind an out-of-process CLI router (durable WAL);
    g2 is configured at the router but never started during the load:

    - ``load``: writes build real fragment bulk on g0/g1 while g2's
      backlog accumulates in the WAL;
    - ``rejoin``: g2 starts on a BLANK data dir; the probe finds
      applied_seq=0 over a non-empty sequence space and drives the
      resync (digest diff -> fragment stream -> seed -> catch-up).
      The tier measures TIME-TO-REJOIN, BYTES STREAMED vs the donor's
      full fragment copy and vs the WAL's replay-it-all alternative,
      asserts ZERO FAILED WRITES during the resync (a writer hammers
      the router the whole time), and asserts digest-level
      convergence in-run.

    ``BENCH_RESYNC_WRITES`` sizes the load; ``BENCH_SMOKE=1`` shrinks
    for CI."""
    import shutil
    import subprocess
    import sys
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    from pilosa_tpu.server.client import Client

    smoke = os.environ.get("BENCH_SMOKE", "").lower() in ("1", "true", "yes")
    n_writes = int(os.environ.get("BENCH_RESYNC_WRITES", "80" if smoke else "800"))
    batch = int(os.environ.get("BENCH_BATCH", "8"))

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "replica_group_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    env.pop("PILOSA_TPU_QCACHE", None)

    def free_port():
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    root = tempfile.mkdtemp(prefix="pilosa_resync_")
    errs = [open(os.path.join(root, f"err{i}.log"), "w+") for i in range(4)]
    group_ports = [free_port() for _ in range(3)]

    def spawn_group(i: int, epoch: int):
        genv = dict(env)
        genv["PILOSA_WORKER_DATA_DIR"] = os.path.join(root, f"g{i}")
        genv["PILOSA_WORKER_HOST"] = f"127.0.0.1:{group_ports[i]}"
        p = subprocess.Popen(
            [sys.executable, worker, f"g{i}@{epoch}"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=errs[i],
            cwd=repo, env=genv, text=True)
        line = json.loads(p.stdout.readline())
        assert line.get("ready"), line
        return p, line["host"]

    procs = []
    tiers = []
    try:
        groups = [spawn_group(i, 1) for i in range(2)]  # g2 stays down
        procs = [p for p, _ in groups]
        hosts = [h for _, h in groups] + [f"127.0.0.1:{group_ports[2]}"]

        router_port = free_port()
        router = subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu", "replica-router",
             "--groups", ",".join(f"g{i}={h}" for i, h in enumerate(hosts)),
             "--port", str(router_port),
             "--wal-dir", os.path.join(root, "wal"),
             "--probe-interval", "0.1"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=errs[3], cwd=repo, env=env, text=True)
        procs.append(router)
        line = router.stdout.readline()
        assert "replica-router" in line, line

        rc = Client(f"127.0.0.1:{router_port}", timeout=60)
        rc.create_index("r")
        rc.create_frame("r", "f")

        def rget(path: str) -> dict:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{router_port}{path}", timeout=10
            ) as resp:
                return json.loads(resp.read())

        def gget(host: str, path: str) -> bytes:
            with urllib.request.urlopen(f"http://{host}{path}", timeout=30) as r:
                return r.read()

        # LOAD: real fragment bulk across several rows/frames while
        # g2's backlog grows in the WAL.
        t0 = time.perf_counter()
        for k in range(0, n_writes, batch):
            q = " ".join(
                f'SetBit(rowID={1 + (c % 5)}, frame="f", columnID={c})'
                for c in range(k, min(k + batch, n_writes))
            )
            rc.execute_query("r", q)
        load_s = time.perf_counter() - t0
        wal_bytes = rget("/replica/status")["wal"]["bytes"]
        donor_digest = json.loads(gget(hosts[0], "/replica/digest"))
        full_copy_bytes = 0
        for path in donor_digest["fragments"]:
            idx, frame, view, slice_i = path.split("/")
            full_copy_bytes += len(gget(
                hosts[0],
                f"/fragment/data?index={idx}&frame={frame}&view={view}&slice={slice_i}",
            ))
        tiers.append({
            "tier": "load", "writes": n_writes, "batch": batch,
            "load_s": round(load_s, 3), "wal_bytes": wal_bytes,
            "full_copy_bytes": full_copy_bytes,
        })

        # REJOIN: start g2 blank; hammer writes the whole time (the
        # tier's zero-failed-writes assertion) until it is back.
        failed = [0]
        extra = [0]
        stop = threading.Event()

        def writer():
            k = n_writes
            while not stop.is_set():
                try:
                    rc.execute_query(
                        "r", f'SetBit(rowID=9, frame="f", columnID={k})'
                    )
                    extra[0] += 1
                except Exception:  # noqa: BLE001 — counted, asserted zero
                    failed[0] += 1
                k += 1

        wt = threading.Thread(target=writer)
        wt.start()
        t_join = time.perf_counter()
        p2, h2 = spawn_group(2, 1)
        procs.append(p2)
        rejoin_s = None
        deadline = time.monotonic() + (120 if smoke else 600)
        while time.monotonic() < deadline:
            g2 = next(g for g in rget("/replica/status")["groups"]
                      if g["name"] == "g2")
            if g2["healthy"] and g2["caughtUp"] and not g2["stale"]:
                rejoin_s = round(time.perf_counter() - t_join, 3)
                break
            time.sleep(0.05)
        stop.set()
        wt.join()
        assert rejoin_s is not None, "g2 never rejoined"
        assert failed[0] == 0, f"{failed[0]} writes failed during resync"
        snap = rget("/debug/vars")
        streamed = snap.get("replica.resync_bytes", 0)
        # CONVERGENCE, digest-level: byte-identical content everywhere.
        digs = {h: json.loads(gget(h, "/replica/digest"))["digest"] for h in hosts}
        assert len(set(digs.values())) == 1, digs
        tiers.append({
            "tier": "rejoin",
            "rejoin_s": rejoin_s,
            "bytes_streamed": streamed,
            "full_copy_bytes": full_copy_bytes,
            "wal_bytes": wal_bytes,
            "resync_fragments": snap.get("replica.resync_fragments", 0),
            "replayed": snap.get("replica.replayed", 0),
            "writes_during_resync": extra[0],
            "failed_writes_during_resync": failed[0],
            "converged": True,
        })
    finally:
        for p in procs:
            try:
                p.kill()
            except Exception:  # noqa: BLE001
                pass
        for p in procs:
            try:
                p.wait(timeout=30)
            except Exception:  # noqa: BLE001
                pass
        for f in errs:
            f.close()
        shutil.rmtree(root, ignore_errors=True)

    by = {t["tier"]: t for t in tiers}
    rj = by["rejoin"]
    return {
        "metric": "resync_rejoin_s",
        "value": rj["rejoin_s"],
        "unit": (
            f"seconds for a BLANK group to rejoin a loaded 2-group cluster "
            f"(streamed {rj['bytes_streamed']} B of roaring fragments vs "
            f"{rj['wal_bytes']} B of WAL replay traffic; "
            f"{rj['writes_during_resync']} writes committed during the "
            f"resync with zero failures; digest convergence asserted in-run)"
        ),
        "bytes_streamed": rj["bytes_streamed"],
        "full_copy_bytes": rj["full_copy_bytes"],
        "wal_bytes": rj["wal_bytes"],
        "cpus": os.cpu_count(),
        "tiers": tiers,
    }


def bench_shard() -> dict:
    """Partitioned replica groups tier: WRITE throughput at 1 shard vs
    2 shards, plus a LIVE RESHARD leg.  Each shard is its own replica
    set with its own sequencer lock and WAL sequence space, so adding a
    shard multiplies write capacity — two shards sequence concurrently
    where one shard serializes everything through a single lock AND a
    single group process:

    - ``router_1s``: one shard, one subprocess group — every write
      through one sequencer (the PR 6-16 write ceiling);
    - ``router_2s``: two shards (slice ranges [0,4) / [4,inf)), one
      subprocess group each — clients split across the ranges, each
      request body stays within one range so it routes whole to its
      owner; acceptance ``scaling_1s_to_2s >= BENCH_SHARD_MIN_SCALING``
      (default 1.5) is ASSERTED in-run on a multi-core host (shards are
      separate processes: a 1-cpu box records the ratio with
      ``skip_reason`` instead — scaling needs cores);
    - ``reshard``: a single open-ended shard splits at slice 4 onto a
      standby group WHILE writer threads hammer the router — zero
      failed writes asserted in-run (fence-held writes just block
      briefly), then digest convergence: the old group's /replica/digest
      holds no moved-range fragment, the new group's holds them all,
      and the router-merged count equals exactly the acked writes.

    BENCH_SMOKE=1 shrinks phases for CI."""
    import subprocess
    import sys
    import tempfile
    import urllib.error
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    from pilosa_tpu.pilosa import SLICE_WIDTH
    from pilosa_tpu.replica.digest import parse_fragment_path

    smoke = os.environ.get("BENCH_SMOKE", "").lower() in ("1", "true", "yes")
    n_clients = int(os.environ.get("BENCH_THREADS", "4" if smoke else "12"))
    phase_s = float(os.environ.get("BENCH_SHARD_SECS", "1.0" if smoke else "6"))
    batch = int(os.environ.get("BENCH_BATCH", "8" if smoke else "32"))
    n_rows = int(os.environ.get("BENCH_ROWS", "8" if smoke else "16"))
    min_scaling = float(os.environ.get("BENCH_SHARD_MIN_SCALING", "1.5"))
    split_at = 4  # slices [0, 4) stay, [4, inf) move / shard away

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "replica_group_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    env.pop("PILOSA_TPU_QCACHE", None)

    def free_port():
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    def spawn_group(name, errfile):
        p = subprocess.Popen(
            [sys.executable, worker, name],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=errfile,
            cwd=repo, env=env, text=True)
        line = json.loads(p.stdout.readline())
        assert line.get("ready"), line
        return p, line["host"]

    def spawn_router(args, errfile):
        port = free_port()
        p = subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu", "replica-router",
             "--port", str(port), *args],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=errfile,
            cwd=repo, env=env, text=True)
        line = p.stdout.readline()
        assert "replica-router" in line, line
        return p, port

    def stop_group(p):
        try:
            p.stdin.write("\n")
            p.stdin.flush()
        except Exception:  # noqa: BLE001 — already dead
            pass
        try:
            p.wait(timeout=30)
        except Exception:  # noqa: BLE001
            p.kill()

    def stop_router(p):
        try:
            p.terminate()
        except Exception:  # noqa: BLE001
            pass
        try:
            p.wait(timeout=30)
        except Exception:  # noqa: BLE001
            p.kill()

    def post(host, path, body, timeout=60):
        req = urllib.request.Request(
            f"http://{host}{path}", data=body, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def seed_schema(host):
        assert post(host, "/index/w", b"{}")[0] == 200
        assert post(host, "/index/w/frame/f", b"{}")[0] == 200

    def query(host, q, qs=""):
        st, body = post(host, f"/index/w/query{qs}", q.encode())
        assert st == 200, body
        return json.loads(body)["results"]

    # Closed-loop write load: client i owns slice (i % len(ranges)) of
    # its range set, every request body stays inside ONE slice range so
    # a 2-shard map routes it whole (the fast path, no splitting), and
    # every columnID is unique per client so acked bits == set bits.
    def write_phase(host, dur_s, row=1):
        t_end = time.perf_counter() + dur_s

        def client(i):
            served = errors = 0
            sl = split_at + (i % split_at) if i % 2 else i % split_at
            k = 0
            while time.perf_counter() < t_end:
                base = sl * SLICE_WIDTH + (i * 1_000_000 + k * batch) % (SLICE_WIDTH - batch)
                body = " ".join(
                    f'SetBit(rowID={(k + j) % n_rows}, frame="f", '
                    f'columnID={base + j})'
                    for j in range(batch)
                ).encode()
                k += 1
                st, _ = post(host, "/index/w/query", body)
                if st == 200:
                    served += 1
                else:
                    errors += 1
            return served, errors

        t0 = time.perf_counter()
        with ThreadPoolExecutor(n_clients) as pool:
            outs = list(pool.map(client, range(n_clients)))
        dt = time.perf_counter() - t0
        served = sum(s for s, _ in outs)
        errors = sum(e for _, e in outs)
        assert errors == 0, f"write phase saw {errors} failed writes"
        return {"write_qps": round(served / dt, 1), "served": served,
                "clients": n_clients, "batch": batch}

    errs = [tempfile.NamedTemporaryFile("w+", delete=False) for _ in range(8)]
    tiers = []
    try:
        # -- tier 1: one shard, one group ---------------------------------
        g0, h0 = spawn_group("gA", errs[0])
        r1, p1 = spawn_router(["--groups", f"gA={h0}"], errs[1])
        host1 = f"127.0.0.1:{p1}"
        seed_schema(host1)
        write_phase(host1, 0.2)  # warm the lane
        tiers.append({"tier": "router_1s", "shards": 1, **write_phase(host1, phase_s)})
        stop_router(r1)
        stop_group(g0)

        # -- tier 2: two shards, one group each ---------------------------
        g1, h1 = spawn_group("gA", errs[2])
        g2, h2 = spawn_group("gB", errs[3])
        r2, p2 = spawn_router(
            ["--shard-map", f"s0=0-{split_at}:gA={h1};s1={split_at}-:gB={h2}"],
            errs[4])
        host2 = f"127.0.0.1:{p2}"
        seed_schema(host2)
        write_phase(host2, 0.2)
        tiers.append({"tier": "router_2s", "shards": 2, **write_phase(host2, phase_s)})
        stop_router(r2)
        stop_group(g1)
        stop_group(g2)

        # -- tier 3: live reshard under write load ------------------------
        g3, h3 = spawn_group("gA", errs[5])
        g4, h4 = spawn_group("gB", errs[6])  # standby split target
        r3, p3 = spawn_router(["--groups", f"gA={h3}"], errs[7])
        host3 = f"127.0.0.1:{p3}"
        seed_schema(host3)
        # Pre-load both halves of the future split so fragments move.
        for sl in range(2 * split_at):
            assert post(
                host3, "/index/w/query",
                f'SetBit(rowID=0, frame="f", columnID={sl * SLICE_WIDTH})'.encode(),
            )[0] == 200

        import threading

        failures, acks = [], [0]
        stop_flag = threading.Event()

        def writer(i):
            k = 0
            while not stop_flag.is_set():
                sl = k % (2 * split_at)  # keep the moved range hot
                col = sl * SLICE_WIDTH + 8 + (i * 500_000 + k) % 400_000
                st, body = post(
                    host3, "/index/w/query",
                    f'SetBit(rowID=2, frame="f", columnID={col})'.encode(),
                )
                if st != 200:
                    failures.append((st, body[:200]))
                elif json.loads(body)["results"] == [True]:
                    acks[0] += 1  # count NEW bits only (dups ack False)
                k += 1

        writers = [threading.Thread(target=writer, args=(i,), daemon=True)
                   for i in range(max(2, n_clients // 4))]
        for t in writers:
            t.start()
        time.sleep(0.3)  # writers in flight before the fence
        t0 = time.perf_counter()
        st, body = post(
            host3, "/replica/reshard",
            json.dumps({
                "shard": "s0", "at": split_at, "name": "s1",
                "groups": [f"gB={h4}"],
            }).encode(),
            timeout=120,
        )
        reshard_ms = round((time.perf_counter() - t0) * 1e3, 1)
        assert st == 200, body
        flip = json.loads(body)
        time.sleep(0.3)  # post-flip writes land through the new map
        stop_flag.set()
        for t in writers:
            t.join(timeout=30)
        assert not failures, (
            f"{len(failures)} writes failed during the live reshard: "
            f"{failures[:3]}"
        )
        # Zero lost writes: router-merged count == acked new bits.
        assert query(host3, 'Count(Bitmap(rowID=2, frame="f"))') == [acks[0]]
        # Digest convergence: the moved range lives ONLY on the new
        # group now — old digest has no moved-range fragment, new
        # digest holds nothing else.
        with urllib.request.urlopen(f"http://{h3}/replica/digest", timeout=30) as resp:
            old_frags = json.loads(resp.read()).get("fragments") or {}
        with urllib.request.urlopen(f"http://{h4}/replica/digest", timeout=30) as resp:
            new_frags = json.loads(resp.read()).get("fragments") or {}
        old_slices = {parse_fragment_path(p)[3] for p in old_frags}
        new_slices = {parse_fragment_path(p)[3] for p in new_frags}
        assert all(s < split_at for s in old_slices), sorted(old_slices)
        assert new_slices and all(s >= split_at for s in new_slices), (
            sorted(new_slices))
        tiers.append({
            "tier": "reshard", "shards": 2,
            "reshard_ms": reshard_ms,
            "fence_ms": flip["fenceMs"],
            "moved_fragments": flip["moved"]["fragments"],
            "moved_bytes": flip["moved"]["bytes"],
            "writes_during_reshard": acks[0],
            "failed_writes": len(failures),
            "map_epoch": flip["mapEpoch"],
        })
        stop_router(r3)
        stop_group(g3)
        stop_group(g4)
    finally:
        for f in errs:
            f.close()
            os.unlink(f.name)

    by = {t["tier"]: t for t in tiers}
    qps_1 = by["router_1s"]["write_qps"]
    qps_2 = by["router_2s"]["write_qps"]
    scaling = round(qps_2 / qps_1, 3) if qps_1 else None
    # Shards are separate PROCESSES: the scaling acceptance needs
    # physical cores (2 groups + router + clients).  A starved box
    # records the ratio and the reason instead of a meaningless assert.
    cpus = os.cpu_count() or 1
    skip_reason = None
    if cpus < 3:
        skip_reason = f"only {cpus} cpu(s): shard scaling needs >= 3 cores"
    elif smoke:
        skip_reason = "BENCH_SMOKE: phases too short for a stable ratio"
    if skip_reason is None:
        assert scaling is not None and scaling >= min_scaling, (
            f"2-shard write scaling x{scaling} < x{min_scaling} "
            f"(router_1s {qps_1} q/s, router_2s {qps_2} q/s on {cpus} cpus)"
        )
    return {
        "metric": "shard_write_qps",
        "value": qps_2,
        "unit": (
            f"write requests/sec via the replica router over 2 slice-shards "
            f"({n_clients} clients, batch {batch}; 1-shard router {qps_1} q/s "
            f"= x{scaling} scaling on {cpus} cpus; live reshard moved "
            f"{by['reshard']['moved_fragments']} fragments with "
            f"{by['reshard']['failed_writes']} failed writes, fence "
            f"{by['reshard']['fence_ms']} ms; zero-loss + digest "
            f"convergence asserted in-run)"
        ),
        "vs_baseline": scaling,
        "scaling_1s_to_2s": scaling,
        "scaling_asserted": skip_reason is None,
        "skip_reason": skip_reason,
        "min_scaling": min_scaling,
        "cpus": cpus,
        "tiers": tiers,
    }


def bench_qcache() -> dict:
    """Query-result-cache tier: a Zipf-skewed repeated read mix (the
    dashboard steady state — the same few queries hit over and over)
    with occasional writes, cache ON (generation-keyed qcache, admission
    floor 0 so CPU-smoke shapes admit) vs OFF on the same request
    schedule.  Reports per-tier hit rate and ms/request; read-your-writes
    is proven in-run (a SetBit touching a cached query's rows forces a
    miss and the next answer reflects the write), and a final numpy
    correctness gate re-checks every pool query.  BENCH_SMOKE=1 shrinks
    the shapes for CI."""
    smoke = os.environ.get("BENCH_SMOKE", "").lower() in ("1", "true", "yes")
    n_slices = int(os.environ.get("BENCH_SLICES", "2" if smoke else "4"))
    n_rows = int(os.environ.get("BENCH_ROWS", "32" if smoke else "64"))
    batch = int(os.environ.get("BENCH_BATCH", "8" if smoke else "32"))
    n_requests = int(os.environ.get("BENCH_ITERS", "400" if smoke else "4000"))
    pool_n = int(os.environ.get("BENCH_QUERY_POOL", "32" if smoke else "128"))
    zipf_s = float(os.environ.get("BENCH_ZIPF_S", "1.1"))
    write_every = int(os.environ.get("BENCH_WRITE_EVERY", "100"))
    bits_per_row = int(
        os.environ.get("BENCH_BITS_PER_ROW", "50" if smoke else "20000")
    )
    import tempfile

    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.pilosa import SLICE_WIDTH
    from pilosa_tpu.qcache import QueryCache

    rng = np.random.default_rng(37)
    reserve = 4096  # import keeps these top columns free for the writes

    # The query pool: pool_n distinct dashboard batches over one frame.
    pool = []
    for seed in range(pool_n):
        prs = np.random.default_rng(1000 + seed).integers(0, n_rows, size=(batch, 2))
        pool.append(" ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for a, b in prs.tolist()
        ))
    # Zipf-skewed schedule over the pool (rank k drawn with p ~ 1/k^s),
    # shared by both tiers so on/off see the same byte-identical stream.
    p = 1.0 / np.arange(1, pool_n + 1) ** zipf_s
    p /= p.sum()
    order = np.random.default_rng(7).choice(pool_n, size=n_requests, p=p)
    state = {"engine": "?"}

    def run(cache_on: bool) -> dict:
        with tempfile.TemporaryDirectory() as d:
            h = Holder(d)
            h.open()
            h.create_index("q").create_frame("f", FrameOptions())
            fr = h.index("q").frame("f")
            rows = np.repeat(np.arange(n_rows, dtype=np.uint64), bits_per_row)
            for s in range(n_slices):
                cols = rng.integers(
                    0, SLICE_WIDTH - reserve, size=len(rows)
                ).astype(np.uint64) + np.uint64(s * SLICE_WIDTH)
                fr.import_bits(rows, cols)
            qc = QueryCache(min_cost_ms=0.0) if cache_on else None
            ex = Executor(h, qcache=qc)
            state["engine"] = ex.engine.name
            # Warm-up: two full pool passes page every row into the
            # device pool, build the Gram, arm the serve lane, trigger
            # every jit shape, and (cache-on) prime the fingerprint memo
            # — then the cache CONTENTS and counters reset, so the timed
            # phase measures steady-state serving (first occurrence of a
            # query is still a real miss, repeats are real hits) instead
            # of the one-time parse/compile cascade.
            for _ in range(2):
                for q in pool:
                    ex.execute("q", q)
            # ... and the write -> repair lane (one warm-up write + a
            # read that repairs the serve state), so the per-tier run
            # doesn't depend on which tier ran first in this process
            # (jit caches are process-wide).
            ex.execute("q", f'SetBit(rowID=0, frame="f", columnID={SLICE_WIDTH - 2})')
            for q in pool[:4]:
                ex.execute("q", q)
            if qc is not None:
                qc.clear()
                qc.hits = qc.misses = qc.bypasses = qc.ineligible = 0
                qc.evictions = qc.stores = 0
            wcount = 0
            lat: list = []
            t0 = time.perf_counter()
            for i, k in enumerate(order.tolist()):
                if write_every and i % write_every == write_every - 1:
                    r = wcount % n_rows
                    c = (SLICE_WIDTH - reserve) + wcount % reserve
                    ex.execute("q", f'SetBit(rowID={r}, frame="f", columnID={c})')
                    wcount += 1
                    continue
                t1 = time.perf_counter()
                ex.execute("q", pool[k])
                lat.append(time.perf_counter() - t1)
            dt = time.perf_counter() - t0
            # Counter snapshot BEFORE the proof/gate queries below add
            # their own hits/misses.
            hits = qc.hits if qc is not None else 0
            misses = qc.misses if qc is not None else 0
            # Read-your-writes proof: cache the hottest query, write a
            # fresh column into BOTH rows of its first pair (the
            # intersection grows by exactly one), and the next answer
            # must reflect it — the write's generation bump forced the
            # miss.
            q0 = pool[int(order[0])]
            c0 = ex.execute("q", q0)
            prs0 = np.random.default_rng(1000 + int(order[0])).integers(
                0, n_rows, size=(batch, 2)
            )
            a, b = int(prs0[0, 0]), int(prs0[0, 1])
            wc = SLICE_WIDTH - 1  # reserved tail: never touched by the import
            ex.execute("q", f'SetBit(rowID={a}, frame="f", columnID={wc})')
            if b != a:
                ex.execute("q", f'SetBit(rowID={b}, frame="f", columnID={wc})')
            c1 = ex.execute("q", q0)
            rw_ok = c1[0] == c0[0] + 1
            # Correctness gate: every pool query (cached or not) matches
            # the numpy sequential path after all the interleaved writes.
            npx = Executor(h, engine="numpy", qcache=None)
            gate_ok = all(
                ex.execute("q", q) == npx.execute("q", q) for q in pool[:8]
            )
            out = {
                "qps": len(lat) / dt,
                "ms_per_request": 1e3 * float(np.mean(lat)),
                "p99_ms": 1e3 * float(np.quantile(lat, 0.99)),
                "hit_rate": hits / (hits + misses) if hits + misses else 0.0,
                "hits": hits,
                "misses": misses,
                "evictions": qc.evictions if qc is not None else 0,
                "cache_bytes": qc.bytes if qc is not None else 0,
                "rw_ok": bool(rw_ok),
                "gate_ok": bool(gate_ok),
            }
            h.close()
        assert out["gate_ok"], "qcache tier diverged from numpy ground truth"
        assert out["rw_ok"], "read-your-writes violated: a write did not force a miss"
        return out

    def trace_overhead_check() -> dict:
        """In-run guard for the request tracer's OFF path: serving with
        a head-sampling tracer at sample-rate 0.01 must cost <= 5% vs
        tracing fully disabled — the unsampled path is a single branch
        per instrumentation site, and this keeps it that way.  Best-of-N
        tight loops over a warm cached query on both sides (min is
        robust to scheduler noise); an absolute per-request escape
        hatch (< 20 us) keeps CI boxes with coarse timers honest."""
        import tempfile

        from pilosa_tpu.trace import Tracer

        n = int(os.environ.get("BENCH_TRACE_ITERS", "1500" if smoke else "6000"))
        with tempfile.TemporaryDirectory() as d:
            h = Holder(d)
            h.open()
            h.create_index("q").create_frame("f", FrameOptions())
            fr = h.index("q").frame("f")
            rows = np.repeat(np.arange(8, dtype=np.uint64), 50)
            fr.import_bits(rows, rng.integers(0, SLICE_WIDTH, size=len(rows)).astype(np.uint64))
            ex = Executor(h, qcache=QueryCache(min_cost_ms=0.0))
            q = pool[0]
            for _ in range(3):
                ex.execute("q", q)  # warm: jit, serve lane, cache entry
            tracer = Tracer(sample_rate=0.01)
            from pilosa_tpu.executor import ExecOptions

            def loop(traced: bool) -> float:
                best = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    if traced:
                        for _i in range(n):
                            tr = tracer.begin(None)  # ~1% sampled
                            if tr is None:
                                ex.execute("q", q)
                            else:
                                ex.execute("q", q, opt=ExecOptions(span=tr.root))
                                tracer.finish_request(
                                    tr, name="bench", dt_ms=tr.root.finish().ms
                                )
                    else:
                        for _i in range(n):
                            ex.execute("q", q)
                    best = min(best, time.perf_counter() - t0)
                return best

            t_off = loop(False)
            t_on = loop(True)
            h.close()
        overhead = t_on / t_off - 1.0
        ok = overhead <= 0.05 or (t_on - t_off) / n <= 20e-6
        assert ok, (
            f"tracing at sample-rate=0.01 cost {overhead * 100:.1f}% vs disabled "
            f"(off {t_off / n * 1e6:.1f} us/req, on {t_on / n * 1e6:.1f} us/req) — "
            "the unsampled path must stay a single branch per site"
        )
        return {"trace_overhead": round(overhead, 4), "trace_ok": ok,
                "trace_sampled": tracer.stat_sampled}

    def costs_overhead_check() -> dict:
        """In-run guard for the observability plane (PR 14): serving
        with the dispatch meter + cost ledger armed AND a Prometheus
        scrape every n/4 requests (a far harsher cadence than a real
        15 s scrape interval) must cost <= 5% vs all of it disabled.
        Same best-of-N / absolute-escape-hatch shape as the trace
        check above."""
        import tempfile

        from pilosa_tpu import metrics as metrics_mod
        from pilosa_tpu.costs import CostLedger
        from pilosa_tpu.executor import ExecOptions
        from pilosa_tpu.stats import ExpvarStatsClient
        from pilosa_tpu.trace import Tracer

        n = int(os.environ.get("BENCH_COSTS_ITERS", "1500" if smoke else "6000"))
        scrape_every = max(1, n // 4)
        with tempfile.TemporaryDirectory() as d:
            h = Holder(d)
            h.open()
            h.create_index("q").create_frame("f", FrameOptions())
            fr = h.index("q").frame("f")
            rows = np.repeat(np.arange(8, dtype=np.uint64), 50)
            fr.import_bits(rows, rng.integers(0, SLICE_WIDTH, size=len(rows)).astype(np.uint64))
            q = pool[0]

            ex_off = Executor(h, qcache=QueryCache(min_cost_ms=0.0))
            stats = ExpvarStatsClient()
            ledger = CostLedger(stats=stats)
            tracer = Tracer(sample_rate=0.01, stats=stats, costs=ledger)
            ex_on = Executor(h, qcache=QueryCache(min_cost_ms=0.0), stats=stats)
            for _ in range(3):
                ex_off.execute("q", q)
                ex_on.execute("q", q)

            def loop(metered: bool) -> float:
                best = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    if metered:
                        for _i in range(n):
                            tr = tracer.begin(None)
                            if tr is None:
                                ex_on.execute("q", q)
                            else:
                                ex_on.execute(
                                    "q", q, opt=ExecOptions(span=tr.root)
                                )
                                tracer.finish_request(
                                    tr, name="bench", dt_ms=tr.root.finish().ms,
                                    body=q.encode(),
                                )
                            if _i % scrape_every == scrape_every - 1:
                                metrics_mod.parse_exposition(
                                    metrics_mod.render(stats)
                                )
                    else:
                        for _i in range(n):
                            ex_off.execute("q", q)
                    best = min(best, time.perf_counter() - t0)
                return best

            t_off = loop(False)
            t_on = loop(True)
            entries = len(ledger)
            h.close()
        overhead = t_on / t_off - 1.0
        ok = overhead <= 0.05 or (t_on - t_off) / n <= 20e-6
        assert ok, (
            f"cost ledger + exposition cost {overhead * 100:.1f}% vs disabled "
            f"(off {t_off / n * 1e6:.1f} us/req, on {t_on / n * 1e6:.1f} us/req) — "
            "metering must stay a branch + a couple of dict ops per dispatch"
        )
        assert entries > 0, "cost ledger folded no traced requests"
        return {"costs_overhead": round(overhead, 4), "costs_ok": ok,
                "costs_entries": entries}

    # Two alternating passes per tier, best-of by ms/request: jit and
    # allocator caches are process-wide, so whichever tier runs first
    # pays residual one-time costs — best-of-two with alternation keeps
    # the A/B honest in one process (same reason _best_of_runs exists).
    offs = [run(False)]
    ons = [run(True)]
    offs.append(run(False))
    ons.append(run(True))
    on = min(ons, key=lambda r: r["ms_per_request"])
    off = min(offs, key=lambda r: r["ms_per_request"])
    trace_ab = trace_overhead_check()
    costs_ab = costs_overhead_check()
    tiers = [
        {"tier": "qcache_on", **{k: (round(v, 3) if isinstance(v, float) else v) for k, v in on.items()}, **trace_ab, **costs_ab},
        {"tier": "qcache_off", **{k: (round(v, 3) if isinstance(v, float) else v) for k, v in off.items()}},
    ]
    speedup = off["ms_per_request"] / on["ms_per_request"]
    return {
        "metric": "qcache_read_qps",
        "value": round(on["qps"], 1),
        "unit": (
            f"requests/sec, Zipf(s={zipf_s}) read mix over {pool_n} distinct "
            f"batch-{batch} queries ({n_slices} slices x {n_rows} rows, one "
            f"write per {write_every} requests; hit_rate {on['hit_rate']:.2f}, "
            f"{on['ms_per_request']:.3f} ms/request vs cache-off "
            f"{off['ms_per_request']:.3f} = x{speedup:.2f}, engine "
            f"{state['engine']})"
        ),
        "vs_baseline": round(speedup, 2),
        "tiers": tiers,
    }


def bench_multicore() -> dict:
    """Multi-core host serving tier: ONE host's serving stack on 1 vs 2
    workers, plus the serve-lane-breadth A/B.

    Part A drives a REAL server (the ``pilosa-tpu server`` CLI — pool,
    QoS door, native serve lane, the whole front door) from T∈{1,2,4}
    closed-loop client threads.  "Worker" means whatever the build can
    actually parallelize: the in-process thread pool on a free-threaded
    CPython, the `[server] workers` SO_REUSEPORT process fallback on a
    GIL build (DEVELOPMENT.md "Multi-core serving" decision table) — the
    same env knobs either way, so the tier measures the deployed shape.
    The headline ``scaling_1_to_2`` (2-worker read QPS / 1-worker, both
    at 4 clients) is asserted >= 1.6 in-run on a multi-core host; a
    1-cpu box records the ratio and the skip reason instead (``cpus``
    says which regime a line measured, like BENCH_CONFIG=replica).

    Part B is the serve-lane-breadth A/B, in-process for determinism:
    each new native one-crossing shape — multi-frame pair batches,
    Range covers, nested tree batches — timed against the Python
    general lane (PILOSA_TPU_NO_FASTLANE=1: full Python parse +
    per-call eval) on the same executor and data.  Native must BEAT the
    Python lane on every shape (asserted in-run); these wins are
    per-core and multiply with part A's worker count."""
    import shutil
    import subprocess
    import sys
    import tempfile
    import urllib.error
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    smoke = os.environ.get("BENCH_SMOKE", "").lower() in ("1", "true", "yes")
    phase_s = float(os.environ.get("BENCH_MULTICORE_SECS", "1.0" if smoke else "6"))
    n_rows = int(os.environ.get("BENCH_ROWS", "8" if smoke else "16"))
    n_slices = int(os.environ.get("BENCH_SLICES", "1" if smoke else "2"))
    batch = int(os.environ.get("BENCH_BATCH", "8" if smoke else "32"))
    bits_per_row = int(os.environ.get("BENCH_BITS_PER_ROW", "500" if smoke else "20000"))
    ab_iters = int(os.environ.get("BENCH_ITERS", "5" if smoke else "20"))
    min_scaling = float(os.environ.get("BENCH_MULTICORE_MIN_SCALING", "1.6"))

    from pilosa_tpu.pilosa import SLICE_WIDTH

    repo = os.path.dirname(os.path.abspath(__file__))
    gil_enabled = getattr(sys, "_is_gil_enabled", lambda: True)()
    free_threaded = not gil_enabled
    worker_mode = "threads" if free_threaded else "processes"

    queries = []
    for seed in range(8):
        prs = np.random.default_rng(seed).integers(0, n_rows, size=(batch, 2))
        queries.append(" ".join(
            f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))'
            for a, b in prs.tolist()
        ))

    def read_phase(host: str, n_clients: int, dur_s: float) -> dict:
        """Closed-loop read load.  A 503 from the pool door counts as a
        shed (the 1-worker tier's bounded queue can legitimately shed
        under 4 closed-loop clients); transport errors stay fatal."""
        t_end = time.perf_counter() + dur_s

        def client(i: int) -> tuple[int, int]:
            served = sheds = 0
            k = i
            while time.perf_counter() < t_end:
                q = queries[k % len(queries)]
                k += 1
                req = urllib.request.Request(
                    f"http://{host}/index/m/query", data=q.encode(), method="POST")
                try:
                    with urllib.request.urlopen(req, timeout=60) as resp:
                        resp.read()
                    served += 1
                except urllib.error.HTTPError as e:
                    assert e.code in (429, 503), f"unexpected status {e.code}"
                    sheds += 1
                except (urllib.error.URLError, OSError) as e:
                    raise AssertionError(f"transport error under load: {e}")
            return served, sheds

        t0 = time.perf_counter()
        with ThreadPoolExecutor(n_clients) as pool:
            outs = list(pool.map(client, range(n_clients)))
        dt = time.perf_counter() - t0
        served = sum(s for s, _ in outs)
        sheds = sum(sh for _, sh in outs)
        assert served > 0, "no reads served"
        return {"read_qps": round(served / dt, 1), "served": served,
                "sheds": sheds, "clients": n_clients}

    data_dir = tempfile.mkdtemp(prefix="bench_multicore_")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    env["PILOSA_DATA_DIR"] = data_dir
    env["PILOSA_HOST"] = "127.0.0.1:0"
    env["PILOSA_ENGINE"] = "numpy"
    env["PILOSA_STATS"] = "expvar"
    env["PILOSA_TPU_QCACHE"] = "0"  # measure execution, not cache hits

    def start_server(workers: int):
        """One serving 'width-w' incarnation of the CLI server."""
        env_s = dict(env)
        # Free-threaded: width = pool threads.  GIL build: width =
        # SO_REUSEPORT processes, one serving thread each, so the 1w
        # baseline and the 2w tier differ ONLY in worker count.
        env_s["PILOSA_TPU_SERVER_MAX_THREADS"] = str(workers if free_threaded else 1)
        env_s["PILOSA_TPU_SERVER_WORKERS"] = str(workers if workers > 1 else 0)
        errf = tempfile.NamedTemporaryFile("w+", delete=False)
        p = subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu", "server"],
            stdout=subprocess.PIPE, stderr=errf, cwd=repo, env=env_s, text=True)
        host = None
        for _ in range(64):
            line = p.stdout.readline()
            if not line:
                break
            if "serving on http://" in line:
                host = line.split("http://", 1)[1].split()[0]
                break
        assert host, f"server (workers={workers}) never reported ready"
        return p, host, errf

    def stop_server(p, errf):
        p.terminate()
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
        errf.close()
        os.unlink(errf.name)

    def warm(host: str, rounds: int = 4):
        """Warm EVERY worker's serve lane (SO_REUSEPORT spreads
        connections, so one pass per worker is not guaranteed — a few
        rounds of the full query set gets all of them hot and the Gram
        serve state armed)."""
        for _ in range(rounds):
            for q in queries:
                req = urllib.request.Request(
                    f"http://{host}/index/m/query", data=q.encode(), method="POST")
                with urllib.request.urlopen(req, timeout=60) as resp:
                    resp.read()

    # Seed ONCE before any server opens: the SO_REUSEPORT siblings each
    # open the same data-dir read-only-by-convention (writes route
    # through the replica router when multi-process consistency matters
    # — DEVELOPMENT.md), so the bench is a pure read workload.
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder

    tiers = []
    try:
        h = Holder(data_dir)
        h.open()
        h.create_index("m").create_frame("f", FrameOptions())
        rng = np.random.default_rng(41)
        rows_l, cols_l = [], []
        for r in range(n_rows):
            for s in range(n_slices):
                cols = rng.integers(0, SLICE_WIDTH - 4096, size=bits_per_row)
                rows_l.extend([r] * bits_per_row)
                cols_l.extend((int(c) + s * SLICE_WIDTH) for c in cols)
        h.index("m").frame("f").import_bits(np.array(rows_l), np.array(cols_l))
        h.close()

        p1, host1, err1 = start_server(1)
        try:
            warm(host1)
            tiers.append({"tier": "serve_1w", "workers": 1,
                          **read_phase(host1, 4, phase_s)})
        finally:
            stop_server(p1, err1)

        p2, host2, err2 = start_server(2)
        try:
            warm(host2)
            for t in (1, 2, 4):
                tiers.append({"tier": f"clients_{t}", "workers": 2,
                              **read_phase(host2, t, phase_s)})
        finally:
            stop_server(p2, err2)
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)

    by = {t["tier"]: t for t in tiers}
    qps_1w = by["serve_1w"]["read_qps"]
    qps_2w = by["clients_4"]["read_qps"]  # same client load as serve_1w
    scaling = round(qps_2w / qps_1w, 3) if qps_1w else None
    cpus = os.cpu_count() or 1
    scaling_skip = None
    if cpus >= 2:
        assert scaling >= min_scaling, (
            f"2-worker reads only x{scaling} of 1-worker on a {cpus}-cpu "
            f"host (need >= {min_scaling})")
    else:
        scaling_skip = (
            f"1-cpu host: {worker_mode} cannot scale by construction; "
            f"ratio x{scaling} recorded, assert skipped")

    # ---- part B: serve-lane breadth vs the Python general lane ----------
    from pilosa_tpu.executor import Executor

    def time_best(fn) -> float:
        best = float("inf")
        for _ in range(ab_iters):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    def lane_ab(ex_native, ex_py, index: str, body: str, tier: str) -> dict:
        """Best-of wall time: native lane vs PILOSA_TPU_NO_FASTLANE=1
        (full Python parse + per-call eval) on the same data.  The B
        side runs on the NUMPY engine — the cheapest Python-lane
        implementation, so the measured win is conservative."""
        got = ex_native.execute(index, body)  # warm: arms serve state / Gram
        got = ex_native.execute(index, body)
        got = ex_native.execute(index, body)
        native_s = time_best(lambda: ex_native.execute(index, body))
        os.environ["PILOSA_TPU_NO_FASTLANE"] = "1"
        try:
            want = ex_py.execute(index, body)  # warm the Python lane too
            py_s = time_best(lambda: ex_py.execute(index, body))
        finally:
            del os.environ["PILOSA_TPU_NO_FASTLANE"]
        assert got == want, f"{tier}: native disagrees with Python lane"
        speedup = py_s / native_s if native_s else float("inf")
        assert speedup > 1.0, (
            f"{tier}: native x{speedup:.2f} does not beat the Python lane "
            f"({native_s * 1e3:.3f} vs {py_s * 1e3:.3f} ms)")
        return {"tier": tier, "native_ms": round(native_s * 1e3, 3),
                "python_ms": round(py_s * 1e3, 3),
                "speedup": round(speedup, 2), "calls": body.count("Count(")}

    bdir = tempfile.mkdtemp(prefix="bench_breadth_")
    try:
        hb = Holder(bdir)
        hb.open()
        rng = np.random.default_rng(7)

        # multi-frame pair batches (pn_serve_multi): one crossing serves
        # a batch that interleaves two frames' armed Gram states.
        ib = hb.create_index("b")
        ib.create_frame("f", FrameOptions())
        ib.create_frame("g", FrameOptions())
        for fn_ in ("f", "g"):
            hb.index("b").frame(fn_).import_bits(
                rng.integers(0, n_rows, 4 * bits_per_row),
                rng.integers(0, n_slices * SLICE_WIDTH, 4 * bits_per_row))
        parts = []
        for a, b in rng.integers(0, n_rows, size=(batch, 2)).tolist():
            parts.append(f'Count(Intersect(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")))')
            parts.append(f'Count(Union(Bitmap(rowID={a}, frame="g"), Bitmap(rowID={b}, frame="g")))')
        # The Gram serve states behind pn_serve_pairs/pn_serve_multi need
        # an engine whose pair_gram works (the numpy engine declines it),
        # so the native side runs the jax executor; the native lane
        # itself is pure C either way.
        exj = Executor(hb, engine="jax")
        exnp = Executor(hb, engine="numpy")
        ab = [lane_ab(exj, exnp, "b", " ".join(parts), "breadth_multiframe")]

        # nested tree batches (pn_serve_tree): fused parse+eval over the
        # armed container table, single-slice index.
        it = hb.create_index("t")
        it.create_frame("f", FrameOptions())
        hb.index("t").frame("f").import_bits(
            rng.integers(0, n_rows, 4 * bits_per_row),
            rng.integers(0, SLICE_WIDTH, 4 * bits_per_row))
        tparts = []
        for a, b, c, d in rng.integers(0, n_rows, size=(batch, 4)).tolist():
            tparts.append(
                f'Count(Intersect(Union(Bitmap(rowID={a}, frame="f"), Bitmap(rowID={b}, frame="f")), '
                f'Difference(Bitmap(rowID={c}, frame="f"), Bitmap(rowID={d}, frame="f"))))')
        ab.append(lane_ab(exnp, exnp, "t", " ".join(tparts), "breadth_tree"))

        # Range covers (pn_pql_match_range): the all-Count(Range) matcher
        # + fused per-view evaluation.
        ir = hb.create_index("r")
        ir.create_frame("tf", FrameOptions(time_quantum="YMD"))
        exr = Executor(hb, engine="numpy")
        stamps = ["2017-01-05T10:00", "2017-02-14T00:00", "2017-03-02T15:00",
                  "2017-06-30T23:00"]
        for r in range(min(n_rows, 4)):
            for ts in stamps:
                for c in rng.integers(0, SLICE_WIDTH, 24).tolist():
                    exr.execute("r", f'SetBit(rowID={r}, frame="tf", columnID={c}, timestamp="{ts}")')
        # Body sized with ``batch`` like the other tiers: the range
        # lane's win is the fused batch parse + view enumeration, a
        # per-call constant, so a handful of calls sits inside timing
        # noise while 4x batch makes the margin decisive.
        rwindows = [("2017-01-01T00:00", "2017-07-01T00:00"),
                    ("2017-02-01T00:00", "2017-03-01T00:00"),
                    ("2017-01-01T00:00", "2017-04-01T00:00"),
                    ("2017-03-01T00:00", "2017-07-01T00:00")]
        rparts = []
        for i in range(4 * batch):
            s_, e_ = rwindows[i % len(rwindows)]
            rparts.append(
                f'Count(Range(rowID={i % min(n_rows, 4)}, frame="tf", '
                f'start="{s_}", end="{e_}"))')
        ab.append(lane_ab(exr, exr, "r", " ".join(rparts), "breadth_range"))
        hb.close()
    finally:
        shutil.rmtree(bdir, ignore_errors=True)

    tiers.extend(ab)
    breadth_min = min(t["speedup"] for t in ab)
    return {
        "metric": "multicore_read_qps",
        "value": qps_2w,
        "unit": (
            f"read requests/sec from one host at 2 {worker_mode[:-2]}s "
            f"(4 clients, batch {batch}; 1-worker {qps_1w} q/s = "
            f"x{scaling} scaling on {cpus} cpus; serve-lane breadth "
            f"native-vs-python x{breadth_min}+ on multiframe/tree/range)"
        ),
        "vs_baseline": scaling,
        "scaling_1_to_2": scaling,
        "scaling_skip": scaling_skip,
        "free_threaded": free_threaded,
        "worker_mode": worker_mode,
        # Worker scaling needs PHYSICAL cores (clients ride the same
        # box); a 1-cpu CI box records ~1.0 by construction and skips
        # the ratio assert with the reason above.
        "cpus": cpus,
        "tiers": tiers,
    }


def bench_bulk() -> dict:
    """BENCH_CONFIG=bulk: the device-build bulk door vs the PR-10
    streamed ingest door on the SAME seeded data, over HTTP against a
    numpy-engine server.

    Three in-run contracts (assertions, not just numbers):
    - THROUGHPUT: the bulk build commits >= BENCH_BULK_MIN_X (default
      5) times the pairs/s of the streamed set_bits door — the whole
      point of packing planes with the sort/segment/scatter kernel and
      deferring roaring materialization.
    - DIFFERENTIAL: the bulk-built frame is digest-identical to the
      streamed frame, slice by slice (materialization happens under the
      checksum touch — the lazy ledger is part of what's being proven).
    - ROUND TRIP: Arrow egress of the bulk frame re-ingested through
      the bulk door re-exports byte-identical per slice.
    """
    import tempfile
    import zlib as _zlib

    from pilosa_tpu.config import Config
    from pilosa_tpu.server.client import Client
    from pilosa_tpu.server.server import Server

    # BENCH_SMOKE=1: tiny shape, throughput gate off — smoke proves the
    # chunk wire + digest parity + arrow round trip, not perf (fixed
    # per-request overheads swamp a 100k-pair run).
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    n_pairs = int(
        os.environ.get("BENCH_BULK_PAIRS", "100000" if smoke else "1000000")
    )
    n_rows = int(os.environ.get("BENCH_BULK_ROWS", "64"))
    n_slices = int(os.environ.get("BENCH_BULK_SLICES", "4"))
    min_x = float(
        os.environ.get("BENCH_BULK_MIN_X", "0" if smoke else "5")
    )
    rng = np.random.default_rng(18)
    rows = rng.integers(0, n_rows, size=n_pairs).astype(np.uint64)
    cols = rng.integers(0, n_slices << 20, size=n_pairs).astype(np.uint64)

    with tempfile.TemporaryDirectory() as d:
        cfg = Config(
            data_dir=d, host="127.0.0.1:0", engine="numpy", stats="expvar",
            qcache_enabled=False,
        )
        srv = Server(cfg)
        srv.open()
        try:
            client = Client(srv.host)
            client.create_index("x")
            for fr in ("s", "b", "r"):
                client.create_frame("x", fr)

            t0 = time.perf_counter()
            res = client.ingest_stream("x", "s", rows, cols, chunk_pairs=65536)
            stream_dt = time.perf_counter() - t0
            assert res["done"], "streamed ingest did not complete"

            t0 = time.perf_counter()
            res = client.bulk_stream("x", "b", rows, cols, chunk_pairs=65536)
            bulk_dt = time.perf_counter() - t0
            assert res["done"], "bulk build did not complete"

            stream_rate = n_pairs / stream_dt
            bulk_rate = n_pairs / bulk_dt
            ratio = bulk_rate / stream_rate
            assert ratio >= min_x, (
                f"bulk build only {ratio:.2f}x the streamed door "
                f"({bulk_rate:,.0f} vs {stream_rate:,.0f} pairs/s); "
                f"need >= {min_x}x"
            )

            # Differential: digest-identical frames, slice by slice.
            # The checksum touch materializes the bulk frame's overlay
            # through the lazy ledger — the contract under test.
            idx = srv.holder.index("x")
            for s in range(n_slices):
                fs = idx.frame("s").view("standard").fragment(s)
                fb = idx.frame("b").view("standard").fragment(s)
                assert fs is not None and fb is not None, f"slice {s} missing"
                assert fs.checksum() == fb.checksum(), (
                    f"bulk-built slice {s} diverged from streamed"
                )

            # Round trip: Arrow egress -> bulk re-ingest -> re-export,
            # byte-identical per slice (deterministic batch framing).
            rt_bytes = 0
            for s in range(n_slices):
                a = client.export_arrow("x", "b", "standard", s)
                crc = _zlib.crc32(a)
                status, out = client.ingest_chunk(
                    "x", "r", 0, len(a), crc, a, ccrc=crc,
                    door="bulk", arrow=True,
                )
                assert status == 200 and out.get("done"), (
                    f"arrow re-ingest of slice {s} failed: {status} {out}"
                )
                rt_bytes += len(a)
            for s in range(n_slices):
                a = client.export_arrow("x", "b", "standard", s)
                b = client.export_arrow("x", "r", "standard", s)
                assert a == b, f"arrow round trip of slice {s} not byte-identical"
        finally:
            srv.close()

    return {
        "metric": "bulk_build_vs_streamed_ingest",
        "value": round(ratio, 2),
        "unit": (
            f"x pairs/s vs /ingest ({bulk_rate:,.0f} vs "
            f"{stream_rate:,.0f} pairs/s over {n_pairs:,} pairs x "
            f"{n_rows} rows x {n_slices} slices; digest-equal; arrow "
            f"round trip {rt_bytes:,} bytes byte-identical)"
        ),
        "tiers": {
            "bulk_pairs_per_s": round(bulk_rate, 1),
            "stream_pairs_per_s": round(stream_rate, 1),
            "bulk_vs_stream": round(ratio, 2),
            "digest_equal": True,
            "arrow_roundtrip_bytes": rt_bytes,
        },
    }


def bench_planner() -> dict:
    """BENCH_CONFIG=planner: the cost-based adaptive planner's closed
    loop (planner/core.py) vs hand-pinned static lanes, on the exact
    front-door path the server handler runs (plan_for -> ExecOptions.plan
    -> executor decision sites -> record fold-back).

    Three query shapes over frames of different row counts stress the
    gram/rmgather trade differently; each shape's ground-truth lane
    comes from two PINNED runs (pin="gram", pin="rmgather") over the
    same mixed schedule.  The adaptive run starts from an EMPTY ledger
    (static-parity start), warms until exploration has sampled both
    lanes past the confidence gate, then a measured phase counts — via
    the ledger's own per-lane fold counts — the fraction of dispatches
    that ran each shape's empirically fastest lane.  Asserts >= 90%
    convergence per shape (shapes whose pinned p50s sit within 10% are
    ties: either lane counts).  Mixed-schedule p50 is reported against
    the best pinned run; BENCH_STRICT=1 additionally asserts it lands
    within 5% (wall-clock -> strict-only, CI boxes are noisy).
    BENCH_SMOKE=1 shrinks the shapes for CI."""
    smoke = os.environ.get("BENCH_SMOKE", "").lower() in ("1", "true", "yes")
    strict = os.environ.get("BENCH_STRICT", "").lower() in ("1", "true", "yes")
    n_slices = int(os.environ.get("BENCH_SLICES", "2" if smoke else "4"))
    queries_per_shape = int(os.environ.get("BENCH_QUERY_POOL", "2" if smoke else "4"))
    measure_passes = int(os.environ.get("BENCH_ITERS", "6" if smoke else "24"))
    bits_per_row = int(
        os.environ.get("BENCH_BITS_PER_ROW", "50" if smoke else "5000")
    )
    # Bench-paced exploration: a tighter tick than the serving default
    # only shortens warm-up (3 alternate-lane samples arrive in
    # 3*explore_every consults); the decision machinery is identical.
    explore_every = int(os.environ.get("BENCH_EXPLORE_EVERY", "6"))
    import tempfile

    from pilosa_tpu import planner as planner_mod
    from pilosa_tpu.core.frame import FrameOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.costs import CostLedger
    from pilosa_tpu.executor import ExecOptions, Executor
    from pilosa_tpu.pilosa import SLICE_WIDTH
    from pilosa_tpu.trace import fingerprint

    # Shapes: (frame, n_rows, batch pairs) — small/medium/large working
    # sets so the static ladder and the measured costs can disagree.
    shapes = [
        ("fa", 16, 4),
        ("fb", 64, 16 if smoke else 24),
        ("fc", 128, 24 if smoke else 48),
    ]

    rng = np.random.default_rng(11)

    def make_pools():
        pools = {}
        for fname, n_rows, batch in shapes:
            pool = []
            for seed in range(queries_per_shape):
                prs = np.random.default_rng(4000 + seed).integers(
                    0, n_rows, size=(batch, 2)
                )
                pool.append(" ".join(
                    f'Count(Intersect(Bitmap(rowID={a}, frame="{fname}"), '
                    f'Bitmap(rowID={b}, frame="{fname}")))'
                    for a, b in prs.tolist()
                ))
            pools[fname] = pool
        return pools

    pools = make_pools()
    # One mixed schedule, shared verbatim by every run (pinned and
    # adaptive see byte-identical request streams).
    schedule = []
    for i in range(measure_passes):
        for fname, _, _ in shapes:
            for q in pools[fname]:
                schedule.append((fname, q))

    state = {"engine": "?"}

    def run(pin: str) -> dict:
        """One full tier: fresh holder + empty ledger + planner (pinned
        or adaptive), front-door consult per request, per-shape p50s and
        per-(fp, lane) ledger fold counts from the measured phase."""
        with tempfile.TemporaryDirectory() as d:
            h = Holder(d)
            h.open()
            idx = h.create_index("p")
            for fname, n_rows, _ in shapes:
                idx.create_frame(fname, FrameOptions())
                fr = h.index("p").frame(fname)
                rows = np.repeat(
                    np.arange(n_rows, dtype=np.uint64), bits_per_row
                )
                for s in range(n_slices):
                    cols = rng.integers(
                        0, SLICE_WIDTH, size=len(rows)
                    ).astype(np.uint64) + np.uint64(s * SLICE_WIDTH)
                    fr.import_bits(rows, cols)
            ledger = CostLedger()
            planner = planner_mod.Planner(
                ledger, pin=pin, explore_every=explore_every,
            )
            ex = Executor(h)
            ex.planner = planner
            state["engine"] = ex.engine.name

            def door(fname: str, q: str) -> float:
                plan = planner.plan_for("p", q.encode())
                t1 = time.perf_counter()
                ex.execute("p", q, opt=ExecOptions(plan=plan))
                return time.perf_counter() - t1

            # Warm-up: jit shapes, device pools, serve states — and for
            # the adaptive run, enough consults that exploration pushes
            # BOTH lanes past the confidence gate (min_samples, default
            # 3, needs 3*explore_every consults per key).
            warm_passes = 3 * explore_every + 2
            for _ in range(warm_passes):
                for fname, q in schedule[: len(shapes) * queries_per_shape]:
                    door(fname, q)
            # Ledger fold counts at the measured phase's start: the
            # delta below counts which lane each dispatch ACTUALLY ran.
            def lane_counts() -> dict:
                out = {}
                for fname, _, _ in shapes:
                    for q in pools[fname]:
                        fp = fingerprint(q.encode())["fp"]
                        for ln in planner_mod.PLAN_LANES:
                            e = ledger.peek(index="p", frame="", fp=fp, lane=ln)
                            out[(fname, fp, ln)] = e["n"] if e else 0
                return out

            before = lane_counts()
            lat: dict[str, list] = {fname: [] for fname, _, _ in shapes}
            mixed: list = []
            for fname, q in schedule:
                dt = door(fname, q)
                lat[fname].append(dt)
                mixed.append(dt)
            delta = {
                k: n - before[k] for k, n in lane_counts().items()
            }
            return {
                "p50": {
                    fname: float(np.percentile(np.array(v), 50) * 1e3)
                    for fname, v in lat.items()
                },
                "mixed_p50": float(np.percentile(np.array(mixed), 50) * 1e3),
                "delta": delta,
                "snapshot": planner.snapshot(limit=16),
            }

    pinned = {ln: run(ln) for ln in planner_mod.PLAN_LANES}
    adaptive = run("")

    # Ground truth per shape: the pinned run with the lower p50; within
    # 10% the lanes tie (on hosts where an eligibility veto degrades a
    # pinned rmgather to gram, both pins measure the same lane and tie
    # by construction).
    convergence = {}
    for fname, _, _ in shapes:
        pg = pinned["gram"]["p50"][fname]
        pr = pinned["rmgather"]["p50"][fname]
        tie = abs(pg - pr) / max(min(pg, pr), 1e-9) < 0.10
        fast = {ln for ln in planner_mod.PLAN_LANES} if tie else (
            {"gram"} if pg <= pr else {"rmgather"}
        )
        on_fast = total = 0
        for (fn, fp, ln), n in adaptive["delta"].items():
            if fn != fname:
                continue
            total += n
            if ln in fast:
                on_fast += n
        frac = on_fast / total if total else 0.0
        convergence[fname] = {
            "fastest": sorted(fast),
            "fraction_on_fastest": round(frac, 3),
            "pinned_p50_ms": {"gram": round(pg, 3), "rmgather": round(pr, 3)},
        }
        assert frac >= 0.90, (
            f"planner converged to the fastest lane on only {frac:.0%} of "
            f"{fname} dispatches (fastest={sorted(fast)}, "
            f"delta={ {k: v for k, v in adaptive['delta'].items() if k[0] == fname} })"
        )

    best_static = min(r["mixed_p50"] for r in pinned.values())
    ratio = adaptive["mixed_p50"] / best_static if best_static > 0 else 1.0
    if strict:
        assert ratio <= 1.05, (
            f"adaptive mixed p50 {adaptive['mixed_p50']:.3f} ms is "
            f"{ratio:.2f}x the best pinned static {best_static:.3f} ms"
        )
    worst = min(
        c["fraction_on_fastest"] for c in convergence.values()
    )
    return {
        "metric": "planner_convergence",
        "value": round(worst, 3),
        "unit": (
            f"min fraction of dispatches on the empirically fastest lane "
            f"across {len(shapes)} shapes (>=0.90 asserted; mixed p50 "
            f"{adaptive['mixed_p50']:.2f} ms vs best pinned "
            f"{best_static:.2f} ms = {ratio:.2f}x; engine "
            f"{state['engine']})"
        ),
        "vs_baseline": round(ratio, 3),
        "tiers": {
            "convergence": convergence,
            "mixed_p50_ms": round(adaptive["mixed_p50"], 3),
            "best_pinned_mixed_p50_ms": round(best_static, 3),
            "adaptive_vs_best_pinned": round(ratio, 3),
            "strict": strict,
        },
    }


def main() -> None:
    cfg = os.environ.get("BENCH_CONFIG", "intersect_count")
    if cfg != "intersect_count":
        result = {
            "setbit": bench_setbit,
            "lockstep": bench_lockstep,
            "lockstep_coalesce": bench_lockstep_coalesce,
            "topn": bench_topn,
            "union64": bench_union64,
            "timerange": bench_timerange,
            "executor": bench_executor,
            "executor_gather": bench_executor_gather,
            "range_executor": bench_range_executor,
            "mixed": bench_mixed,
            "writelane": bench_writelane,
            "overload": bench_overload,
            "tenancy": bench_tenancy,
            "qcache": bench_qcache,
            "replica": bench_replica,
            "multicore": bench_multicore,
            "recovery": bench_recovery,
            "resync": bench_resync,
            "bulk": bench_bulk,
            "planner": bench_planner,
            "shard": bench_shard,
            "intersect_count_stream": bench_intersect_stream,
            "intersect_count_4krows": bench_intersect_4krows,
            "topn_p50": bench_topn_p50,
        }[cfg]()
        print(json.dumps(result))
        return
    n_slices = int(os.environ.get("BENCH_SLICES", "16"))
    n_rows = int(os.environ.get("BENCH_ROWS", "64"))
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    # Shapes past device memory switch to the slice-streaming executor
    # regime — the same decision the product mapReduce makes.  The
    # resident ceiling is the matrix itself (~14 GB usable of 15.75 GB
    # HBM): since round 3 the kernels take the matrix in its born-tiled
    # 4D form, so XLA no longer materializes a relayout copy that used to
    # double the footprint (the round-2 1024-slice OOM).
    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE as _W

    resident_max = int(os.environ.get("BENCH_RESIDENT_MAX", str(14 << 30)))
    if n_slices * n_rows * _W * 4 > resident_max:
        print(json.dumps(bench_intersect_stream()))
        return
    # Long enough that the one-dispatch stream's fixed costs (the ~120 ms
    # dispatch+fetch round trip through the tunnel, and the hoisted Gram
    # build) amortize.  With the Gram strategy a batch step is ~1.7 us of
    # device time (256 table lookups), so a sustained-rate measurement
    # needs a LONG stream: 262144 steps = ~450 ms of device work vs the
    # ~120 ms RTT.  Shorter streams measure the tunnel round trip and
    # scale with stream length — the r01 2.8M / r03 5-19M spread on
    # identical code was exactly that artifact.
    iters = int(os.environ.get("BENCH_ITERS", "262144"))
    # Bit density ~2^-k via AND of k random words (throughput over packed
    # words is density-independent; this just keeps counts realistic).
    density_k = int(os.environ.get("BENCH_DENSITY_K", "4"))

    from pilosa_tpu.ops.bitwise import WORDS_PER_SLICE

    W = WORDS_PER_SLICE  # 32768 words = 2^20 bits per slice-row
    rng = np.random.default_rng(42)

    # ---- TPU path -------------------------------------------------------
    import jax
    import jax.numpy as jnp
    from jax import lax

    from pilosa_tpu.ops import dispatch

    # Billion-column matrices are generated ON DEVICE: uploading 8 GB
    # through this environment's ~4 MiB/s tunnel takes >30 min (a real
    # host-attached TPU fills HBM in <1 s over PCIe, so host-gen would
    # measure nothing real).  Small shapes keep the host path so the full
    # numpy baseline and whole-stream correctness gate apply.
    hostgen_max = int(os.environ.get("BENCH_HOSTGEN_MAX", str(1 << 30)))
    device_gen = n_slices * n_rows * W * 4 > hostgen_max

    from pilosa_tpu.ops import bitwise as _bw
    from pilosa_tpu.ops.dispatch import _use_gram

    gram_mode = _use_gram(n_slices, n_rows, W, batch)

    @jax.jit
    def run_stream_gram(g, pairs_stream):
        # Gram strategy with the build hoisted EXPLICITLY: at big slice
        # counts the chunked Gram build is itself a while loop, which XLA
        # does not hoist out of the query scan (it would rebuild the Gram
        # every step) — so the bench mirrors the product executor: build
        # once (run_gram_build below), stream lookups against it.
        def step(carry, prs):
            return carry, _bw.gram_pair_counts("and", g, prs)

        out = lax.scan(step, 0, pairs_stream)[1]
        return out, out.astype(jnp.int64).sum()

    @jax.jit
    def run_stream(rm, pairs_stream):
        def step(carry, prs):
            return carry, dispatch.gather_count("and", rm, prs, allow_gram=False)

        out = lax.scan(step, 0, pairs_stream)[1]
        # Digest depends on EVERY step: fetching it synchronizes on the
        # whole stream while the full per-query results stay materialized
        # in HBM (a returned output — XLA cannot elide it).
        return out, out.astype(jnp.int64).sum()


    if device_gen:
        @jax.jit
        def gen_matrix(key):
            rm = jax.random.bits(key, (n_slices, n_rows, W // 128, 128), jnp.uint32)
            for i in range(density_k - 1):
                rm &= jax.random.bits(
                    jax.random.fold_in(key, i + 1),
                    (n_slices, n_rows, W // 128, 128),
                    jnp.uint32,
                )
            return rm

        drm = gen_matrix(jax.random.PRNGKey(42))
        # Host mirror of the FIRST slice only (for the correctness gate).
        row_matrix = np.asarray(drm[:1]).reshape(1, n_rows, W)
    else:
        row_matrix = rng.integers(0, 1 << 32, size=(n_slices, n_rows, W), dtype=np.uint32)
        for _ in range(density_k - 1):
            row_matrix &= rng.integers(
                0, 1 << 32, size=(n_slices, n_rows, W), dtype=np.uint32
            )
        # Born-tiled 4D device form: no relayout copy inside jit.
        drm = jax.device_put(row_matrix.reshape(n_slices, n_rows, W // 128, 128))
    # Pair stream generated on device (the host array would be
    # iters*batch*8 bytes — half a GB at the default length, minutes of
    # tunnel upload); the correctness gate fetches only the rows it needs.
    @jax.jit
    def gen_pairs(key):
        return jax.random.randint(key, (iters, batch, 2), 0, n_rows, jnp.int32)

    dpairs = gen_pairs(jax.random.PRNGKey(7))
    all_pairs = np.asarray(dpairs[: max(1, min(3, iters))])  # gate mirror
    if gram_mode:
        # Build once, like the product executor's cached Gram; steady
        # state streams lookups against the device-resident [R, R].
        dgram = jax.jit(_bw.pair_gram)(drm)
        t0 = time.perf_counter()
        np.asarray(jax.jit(_bw.pair_gram)(drm).sum())  # timed rebuild
        gram_build_s = time.perf_counter() - t0
        launch = lambda: run_stream_gram(dgram, dpairs)
    else:
        gram_build_s = 0.0
        launch = lambda: run_stream(drm, dpairs)
    # Warmup compiles and runs the full stream once.
    out_dev, _ = launch()
    out = np.asarray(out_dev[: len(all_pairs)])

    # Timed region: dispatch the stream and fetch the 8-byte digest.  The
    # digest is data-dependent on all iters*batch per-query results, so
    # timing stops only when the device has computed and materialized
    # every result in HBM.  The full result tensor is deliberately NOT
    # fetched inside the timer: this chip sits behind a remote tunnel
    # whose measured result-download rate is 2-7 MiB/s (vs >100 GB/s for
    # a host-attached TPU over PCIe), so fetching the [iters, batch]
    # int32 tensor (~2.6 MB at the default shape) would time the tunnel,
    # not the engine — that artifact is exactly what made the r01/r02
    # official captures swing 2.8M -> 141k q/s on identical code (see
    # BASELINE.md round-3 note).  Results ARE on-device and a real
    # (host-attached) server would stream them to clients at PCIe rates.
    #
    # Best of N timed runs (min wall time): the tunnel adds tens of ms of
    # dispatch jitter, so a single draw under-reports the sustained rate.
    def timed():
        out_d, digest = launch()
        np.asarray(digest)
        return out_d

    dt, out_dev = _best_of_runs(timed)
    qps = iters * batch / dt
    # Post-timing fetch for the correctness gate: only the gated prefix
    # (the full tensor is ~270 MB at the default stream length — minutes
    # through the tunnel for bytes the gate never looks at).
    out = np.asarray(out_dev[: max(1, min(3, iters))])

    # ---- CPU numpy baseline (single-threaded popcount loop) -------------
    from pilosa_tpu.roaring import _POPCNT8

    base_slices = row_matrix.shape[0]  # all slices, or 1 when device_gen

    def numpy_batch(i):
        p = all_pairs[i]
        a = row_matrix[:, p[:, 0], :]
        b = row_matrix[:, p[:, 1], :]
        inter = a & b
        return _POPCNT8[inter.view(np.uint8)].reshape(base_slices, batch, -1).sum(axis=(0, 2))

    base_iters = max(1, min(3, iters))
    numpy_batch(0)  # warm: first-touch page faults + LUT cache
    t0 = time.perf_counter()
    base_out = None
    for i in range(base_iters):
        base_out = numpy_batch(i)
    base_dt = time.perf_counter() - t0
    # Extrapolate the single-slice host mirror to the full slice count
    # (the numpy loop is linear in slices; device_gen shapes would need
    # hours of LUT work for an exact all-slice baseline).
    base_qps = base_iters * batch / (base_dt * n_slices / base_slices)
    if device_gen:
        # Gate against the slice-0 mirror: same pairs, device counts
        # restricted to slice 0 must equal the numpy counts.
        gate = np.asarray(
            dispatch.gather_count("and", drm[:1], jnp.asarray(all_pairs[base_iters - 1]),
                                  allow_gram=False)
        )
        assert np.array_equal(gate, base_out), "TPU/CPU result mismatch (slice 0)"
        if gram_mode:
            # And the Gram lookups must equal the direct kernel over the
            # FULL matrix (the all-slice ground truth numpy can't afford).
            kq = np.asarray(
                dispatch.gather_count(
                    "and", drm, jnp.asarray(all_pairs[0]), allow_gram=False
                )
            )
            assert np.array_equal(out[0], kq), "gram/kernel mismatch"
    else:
        assert np.array_equal(out[base_iters - 1], base_out), "TPU/CPU result mismatch"

    unit = f"queries/sec ({n_slices} slices x 2^20 cols, batch {batch}"
    if gram_mode and gram_build_s > 0.01:
        unit += f", one-time chunked Gram build {gram_build_s:.2f}s"
    unit += f", backend {jax.default_backend()})"
    # Headline denominator: the measured compiled reference loop (one
    # core), not the numpy stand-in — see module docstring.  A reference
    # pair count at this shape streams both operands once:
    # 2 * n_slices * 128 KiB per query through the AND+POPCNT loop.
    ref_bps = _ref_loop_bytes_per_s()
    ref_qps = ref_bps / (2.0 * n_slices * W * 4)
    result = {
        "metric": "intersect_count_qps",
        "value": round(qps, 1),
        "unit": unit,
        "vs_baseline": round(qps / ref_qps, 2),
        "vs_numpy": round(qps / base_qps, 2),
        "ref_loop_qps_1core": round(ref_qps, 1),
        "ref_loop_measured": getattr(_ref_loop_bytes_per_s, "_measured", False),
    }
    # HBM-bandwidth accounting is only meaningful when the strategy
    # actually MOVES the bitmaps per batch: with the Gram shortcut active
    # each query is a table lookup, so bandwidth_util is reported null
    # (the honest answer — see BASELINE.md's strategy ablation).
    # The resident-vs-gather split mirrors dispatch's ACTUAL strategy
    # predicate (resident_strategy includes the VMEM-fit clause, not just
    # the row/batch ratio) so the traffic formula matches the kernel that
    # ran.
    from pilosa_tpu.ops.pallas_kernels import resident_strategy as _resident

    if not gram_mode:
        if _resident(n_rows, W, batch):  # resident: whole row set per batch
            bytes_moved = iters * n_slices * n_rows * W * 4
        else:  # gather kernel: two operand rows per (query, slice)
            bytes_moved = iters * batch * 2 * n_slices * W * 4
        result["bandwidth_util"] = round(bytes_moved / dt / HBM_ROOFLINE, 4)
    else:
        result["bandwidth_util"] = None

    # ---- tier scoreboard ------------------------------------------------
    # One flattering scalar is not a scoreboard (VERDICT r3 item 5): the
    # driver artifact carries every serving tier with its own util so
    # round-over-round numbers stay comparable regardless of which lane
    # is fastest that day.  Tiers run on the DRIVER's default invocation
    # (no shape env overrides) — big-shape runs via run_big_benches.sh
    # must not leak their BENCH_SLICES/ROWS/ITERS into the 4k-row tier
    # shapes (a 1024-slice x 4096-row tier matrix would be ~0.5 TB).
    # BENCH_TIERS=1/0 forces either way.
    tiers_on = os.environ.get(
        "BENCH_TIERS",
        "0" if any(
            os.environ.get(k) for k in ("BENCH_SLICES", "BENCH_ROWS", "BENCH_ITERS")
        ) else "1",
    ) not in ("0", "false", "no")
    if tiers_on:
        # Label by what actually served the headline: the dispatch
        # strategy predicate mirrors the bandwidth accounting above
        # (NO_GRAM tall-row shapes run the gather kernel, not resident).
        if gram_mode:
            head_tier = "gram"
            head_note = "all-pairs MXU Gram, host/table lookup serving (no per-query bitmap traffic)"
        elif _resident(n_rows, W, batch):
            head_tier = "resident_nogram"
            head_note = "direct resident kernel headline (PILOSA_TPU_NO_GRAM)"
        else:
            head_tier = "gather_nogram"
            head_note = "direct gather kernel headline (PILOSA_TPU_NO_GRAM)"
        tiers = [{
            "tier": head_tier,
            "qps": result["value"],
            "bandwidth_util": result["bandwidth_util"],
            "note": head_note,
        }]
        iters_t = max(1, min(iters, int(os.environ.get("BENCH_TIER_ITERS", "2048"))))
        if gram_mode:
            # Resident/no-Gram tier: the direct kernel on the SAME shape.
            dp_t = dpairs[:iters_t]
            out_t, _ = run_stream(drm, dp_t)  # compile + warm
            def timed_t():
                out_d, digest = run_stream(drm, dp_t)
                np.asarray(digest)
                return out_d
            dt_t, out_t = _best_of_runs(timed_t)
            if _resident(n_rows, W, batch):
                moved = iters_t * n_slices * n_rows * W * 4
            else:
                moved = iters_t * batch * 2 * n_slices * W * 4
            tiers.append({
                "tier": "resident_nogram",
                "qps": round(iters_t * batch / dt_t, 1),
                "bandwidth_util": round(moved / dt_t / HBM_ROOFLINE, 4),
            })
        # 4k-row gather tiers: the Gram-ineligible tall-row-set shape, in
        # both kernel layouts (row-major = the descriptor-rate record).
        t4 = bench_intersect_4krows()
        tiers.append({
            "tier": "gather_4krows_rowmajor",
            "qps": t4["value"],
            "bandwidth_util": t4.get("bandwidth_util"),
        })
        s4 = int(os.environ.get("BENCH_SLICES", "4"))
        r4 = int(os.environ.get("BENCH_ROWS", "4096"))
        b4 = batch
        it4 = max(1, min(iters_t, int(os.environ.get("BENCH_ITERS", "256"))))
        @jax.jit
        def gen_sm(key):
            return jax.random.bits(key, (s4, r4, W // 128, 128), jnp.uint32)
        dsm = gen_sm(jax.random.PRNGKey(43))
        p4 = jax.device_put(
            np.random.default_rng(9).integers(0, r4, size=(it4, b4, 2), dtype=np.int32)
        )
        @jax.jit
        def run_sm(rm, ps):
            def step(carry, prs):
                return carry, dispatch.gather_count("and", rm, prs, allow_gram=False)
            out2 = lax.scan(step, 0, ps)[1]
            return out2, out2.astype(jnp.int64).sum()
        run_sm(dsm, p4)  # compile + warm
        def timed_sm():
            out_d, digest = run_sm(dsm, p4)
            np.asarray(digest)
            return out_d
        dt_sm, _ = _best_of_runs(timed_sm)
        moved_sm = it4 * b4 * 2 * s4 * W * 4
        tiers.append({
            "tier": "gather_4krows_slicemajor",
            "qps": round(it4 * b4 / dt_sm, 1),
            "bandwidth_util": round(moved_sm / dt_sm / HBM_ROOFLINE, 4),
        })
        result["tiers"] = tiers
    print(json.dumps(result))


if __name__ == "__main__":
    main()
