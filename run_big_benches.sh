#!/bin/bash
# One-off big-shape bench runs.  Results append to big_bench_results.jsonl.
#
# GUARD: takes an exclusive lock for the whole run and refuses to start if
# another holder exists.  Round 2's stream config (17 GiB host uploads)
# overlapped the driver's official bench capture and collapsed the
# recorded headline 20x (BASELINE.md round-3 note); any long background
# bench MUST hold this lock, and interactive captures should `flock -n`
# the same file to detect contention.
set -u
cd /root/repo
LOCK=/tmp/pilosa_bench.lock
exec 9>"$LOCK"
if ! flock -n 9; then
  echo "another bench run holds $LOCK; refusing to overlap" >&2
  exit 1
fi
OUT=big_bench_results.jsonl
# PREFLIGHT: the invariant linter must be clean before burning bench
# hours — a stale counters registry or a new untagged finding means the
# tree is mid-change and the run's telemetry names may not match
# COUNTERS.md.  Covers all generation-2 rules too (guarded-fields,
# native-abi, stale-suppression).  Fails fast with the linter's report.
if ! python -m pilosa_tpu.analysis; then
  echo "pilosa_tpu.analysis preflight failed; fix/tag findings first" >&2
  exit 1
fi
# PREFLIGHT 2: the native boundary must be sanitizer-clean before the
# writelane/ingest configs hammer it for an hour — build the ASAN+UBSAN
# flavor and re-run the differential suites against it (the same leg
# tier-1 runs; skips itself with a logged reason when no toolchain).
if ! python -m pytest tests/test_native_sanitized.py -q -p no:cacheprovider; then
  echo "sanitized native leg failed; fix the sanitizer findings first" >&2
  exit 1
fi
# PREFLIGHT 3: the interleaving-explorer scenario suite + the replica
# write-protocol model check must pass before any bench run — the
# recovery/resync/writelane configs hammer exactly the sequencer/WAL/
# catch-up orderings the explorer covers, and a schedule-dependent bug
# should fail HERE with a replayable schedule string, not corrupt an
# hour of bench telemetry.  (Same lane as tier-1's test_sched gate.)
if ! python -m pilosa_tpu.analysis --explore all; then
  echo "interleaving explorer / protocol model preflight failed;" >&2
  echo "replay the printed schedule: python -m pilosa_tpu.analysis --explore <scenario> --schedule <string>" >&2
  exit 1
fi
# PREFLIGHT 4: the observability plane must scrape clean before an hour
# of telemetry rides it — stand up a 3-group bench-shaped cluster with
# one group DOWN, strict-parse every /metrics exposition (group + router)
# and require /debug/fleet to serve a PARTIAL aggregate with the dead
# group stamped stale.  Unparseable exposition or a fleet view that
# drops the dead group fails here, not in the dashboard at hour two.
if ! python - <<'PYEOF'
import json, sys, tempfile, urllib.request

from pilosa_tpu import metrics
from pilosa_tpu.config import Config
from pilosa_tpu.replica import ReplicaRouter
from pilosa_tpu.server.server import Server
from pilosa_tpu.stats import ExpvarStatsClient

with tempfile.TemporaryDirectory() as tmp:
    servers = []
    for i in range(3):
        cfg = Config(data_dir=f"{tmp}/g{i}", host="127.0.0.1:0",
                     engine="numpy", stats="expvar", qcache_enabled=False,
                     replica_group=f"g{i}")
        srv = Server(cfg)
        srv.open()
        servers.append(srv)
    router = ReplicaRouter(
        [f"g{i}={s.host}" for i, s in enumerate(servers)],
        probe_interval_s=0.1, stats=ExpvarStatsClient(),
    ).serve()
    base = f"http://127.0.0.1:{router.port}"
    try:
        def req(method, path, body=None):
            rq = urllib.request.Request(base + path, data=body, method=method)
            with urllib.request.urlopen(rq, timeout=30) as resp:
                return resp.read()

        req("POST", "/index/i", b"{}")
        req("POST", "/index/i/frame/f", b"{}")
        req("POST", "/index/i/query", b'SetBit(rowID=1, frame="f", columnID=1)')
        req("POST", "/index/i/query", b'Count(Bitmap(rowID=1, frame="f"))')
        # Strict-parse every exposition in the fleet: each group's and
        # the router's own.
        for s in servers:
            fams = metrics.parse_exposition(
                urllib.request.urlopen(
                    f"http://{s.host}/metrics", timeout=30).read().decode())
            assert fams, f"empty exposition from group {s.host}"
        metrics.parse_exposition(req("GET", "/metrics").decode())
        # Kill one group; the fleet view must degrade to PARTIAL with
        # the dead group still present, stamped with its error.
        servers[2].close()
        fleet = json.loads(req("GET", "/debug/fleet?timeout-ms=300"))
        assert len(fleet["groups"]) == 3, fleet
        assert fleet["partial"] is True, "fleet view not marked partial"
        dead = [g for g in fleet["groups"] if g["name"] == "g2"][0]
        assert dead.get("error") and dead["staleScrape"], dead
        live = [g for g in fleet["groups"] if g["name"] != "g2"]
        assert all(g["scrape"] is not None for g in live), "live scrape missing"
        print("observability preflight OK:",
              sum(1 for g in fleet['groups'] if not g['staleScrape']),
              "of 3 groups scraped live")
    finally:
        router.close()
        for s in servers[:2]:
            s.close()
PYEOF
then
  echo "observability preflight failed: /metrics unparseable or /debug/fleet" >&2
  echo "did not degrade to a partial aggregate; fix before burning bench hours" >&2
  exit 1
fi
# PREFLIGHT 5: the native kernels must be THREAD-sanitizer clean before
# the concurrent configs drive them from real overlapping threads for an
# hour — build the TSAN flavor and run the true-concurrency harness
# (clean per-fragment leg + the seeded shared-table race fixture that
# proves the leg can see a race at all).  Skips itself with a logged
# reason when the toolchain or the TSAN runtime is missing, same
# contract as the ASAN leg; a real data race fails HERE with the TSAN
# report, not as silent corruption in hour two.
if ! python -m pytest tests/test_native_threaded.py -q -p no:cacheprovider; then
  echo "TSAN native leg failed: a data race (or a blind TSAN fixture) in the" >&2
  echo "concurrent kernel paths; fix the race before burning bench hours" >&2
  exit 1
fi
run() {
  echo "=== $* $(date +%H:%M:%S)" >> $OUT
  timeout 3600 env "$@" python bench.py >> $OUT 2>>big_bench_errors.log
  echo "--- exit=$? $(date +%H:%M:%S)" >> $OUT
}
# 1) >=1B columns resident on one chip (device-generated; relayout copy
#    gone since round 3, so 1024 slices x 64 rows = 8 GB fits).  Long
#    stream for the Gram lane's sustained rate; the NO_GRAM line records
#    the direct resident kernel's bandwidth on the same shape.
run BENCH_CONFIG=intersect_count BENCH_SLICES=1024 BENCH_ITERS=65536 BENCH_TIMED_RUNS=3
run BENCH_CONFIG=intersect_count BENCH_SLICES=1024 PILOSA_TPU_NO_GRAM=1 BENCH_ITERS=128 BENCH_TIMED_RUNS=2
# 2) TopN p50 @ 1.01B columns (BASELINE.json metric).
run BENCH_CONFIG=topn_p50 BENCH_ITERS=64
# 3) Gram-ineligible 4k-row gather headline with bandwidth_util, at the
#    512 KB-row and 2 MB-row DMA shapes.
run BENCH_CONFIG=intersect_count_4krows BENCH_TIMED_RUNS=3
run BENCH_CONFIG=intersect_count_4krows BENCH_SLICES=16 BENCH_TIMED_RUNS=3
# 4) Resident-kernel bandwidth_util at the classic 16-slice shape.
run BENCH_CONFIG=intersect_count PILOSA_TPU_NO_GRAM=1 BENCH_ITERS=512 BENCH_TIMED_RUNS=3
# 5) Bigger-than-HBM stream (device-staged chunks; measures the HBM
#    streaming regime, not the tunnel) — at 2.15B and the 10B-column
#    north-star scale.
run BENCH_CONFIG=intersect_count_stream BENCH_TIMED_RUNS=2
run BENCH_CONFIG=intersect_count_stream BENCH_SLICES=10240 BENCH_TIMED_RUNS=2
# 6) Product-path gather-regime shapes (chunked-Gram product lane, with
#    forced-NO_GRAM row-major/slice-major tiers recorded in the unit).
run BENCH_CONFIG=executor_gather BENCH_ROWS=1024
run BENCH_CONFIG=executor_gather
# 7) Mixed read/write serving: warm-state repair lane vs forced
#    invalidate-and-rebuild, at 95/5, 50/50, and write-burst coalescing
#    tiers (b8/b64 — one deferred repair per burst; tiers in the JSON);
#    the second line stresses a wider Gram (more rows) per repair and a
#    wider slice span (where per-(row, slice) patch granularity pays).
run BENCH_CONFIG=mixed
run BENCH_CONFIG=mixed BENCH_ROWS=256 BENCH_SLICES=8
# 8) Lockstep request coalescing: single-call requests, coalesced batch
#    replay vs one control-plane entry per request.
run BENCH_CONFIG=lockstep_coalesce
run BENCH_CONFIG=lockstep_coalesce BENCH_THREADS=32
# 8b) Native write request lane + streaming columnar ingest: singleton
#    native-vs-general and batched native-vs-python A/B (both asserted
#    in-run), plus the /ingest streaming tier sustaining a column
#    stream against concurrent QoS-doored reads (zero read sheds
#    asserted).  The second line sizes bigger batches; the third a
#    bigger stream with more readers.
run BENCH_CONFIG=writelane
run BENCH_CONFIG=writelane BENCH_BATCH=256
run BENCH_CONFIG=writelane BENCH_STREAM_PAIRS=2000000 BENCH_THREADS=8
# 9) Generation-keyed query result cache: Zipf-skewed repeated read mix
#    with interleaved writes, cache-on vs cache-off tiers in the JSON
#    (hit rate + ms/request; read-your-writes asserted in-run); the
#    second line pushes a wider pool at heavier skew (dashboard-fleet
#    shape), the third an unskewed mix (worst case for the cache).
run BENCH_CONFIG=qcache
run BENCH_CONFIG=qcache BENCH_QUERY_POOL=512 BENCH_ZIPF_S=1.3
run BENCH_CONFIG=qcache BENCH_ZIPF_S=0.0
#    Tracing on/off A/B rides the qcache config (trace_overhead /
#    trace_ok in the qcache_on tier): head sampling at 0.01 must stay
#    within 5% of tracing disabled — bigger loop for a tighter bound.
#    The observability-plane A/B rides the same config (costs_overhead /
#    costs_ok): dispatch meter + cost ledger + a scrape every n/4
#    requests must also stay within 5% of fully disabled.
run BENCH_CONFIG=qcache BENCH_TRACE_ITERS=40000 BENCH_COSTS_ITERS=40000
# 10) Request-lifecycle QoS under overload: a real HTTP server at 2x door
#    capacity, QoS on (bounded admission + deadlines; shed 429s, p99 near
#    presat) vs off (unbounded; p99 degrades with the queue).  The second
#    line pushes deeper overload on a wider door.
run BENCH_CONFIG=overload
run BENCH_CONFIG=overload BENCH_QOS_DEPTH=8 BENCH_THREADS=64
# 10b) Multi-tenant hostile neighbor: a polite tenant at its weighted
#    fair share of the read door vs a hostile tenant flooding at 2x the
#    door's depth.  The hostile-flood leg asserts IN-RUN that isolation
#    holds: polite p99 within 1.5x its isolated baseline, ZERO polite
#    sheds, and real hostile sheds — then repeats with tenancy off for
#    the A/B.  The second line widens the door and doubles the flood.
run BENCH_CONFIG=tenancy
run BENCH_CONFIG=tenancy BENCH_QOS_DEPTH=16 BENCH_THREADS=32
# 11) Replicated serving groups: read QPS through the replica router at
#    1 vs 2 groups (scaling_1_to_2 is the headline; needs >= 3 cores) +
#    router on/off overhead, with cross-group read-your-writes and
#    failover (reads survive a killed group, writes 503 until quorate)
#    asserted in-run.  The second line scales the group fleet.
run BENCH_CONFIG=replica
run BENCH_CONFIG=replica BENCH_GROUPS=4 BENCH_THREADS=32
# 11b) Multi-core host serving: one host's front door at 1 vs 2 workers
#    (free-threaded pool threads, or SO_REUSEPORT processes on GIL
#    builds) from 1/2/4 client threads — scaling_1_to_2 asserted >= 1.6
#    in-run on a multi-core host — plus the serve-lane-breadth A/B
#    (native multi-frame / tree / Range one-crossing lanes vs the
#    Python general lane, parity + win asserted in-run).  The second
#    line sizes bigger batches over more rows (dashboard shape).
run BENCH_CONFIG=multicore
run BENCH_CONFIG=multicore BENCH_ROWS=64 BENCH_BATCH=128 BENCH_BITS_PER_ROW=50000
# 12) Durable write log + recovery: write throughput with 3 groups vs a
#    SIGKILLed group on the degraded quorum (zero failed writes asserted
#    in-run — the WAL's availability headline) and catch-up time for the
#    restarted group's WAL-suffix replay; the second line sizes a deeper
#    backlog so the replay phase dominates.
run BENCH_CONFIG=recovery
run BENCH_CONFIG=recovery BENCH_RECOVERY_WRITES=4000 BENCH_BATCH=16
# 13) Automated resync: a BLANK group joins a loaded 2-group cluster
#    and self-heals (digest diff -> roaring fragment stream -> seed ->
#    catch-up) — time-to-rejoin, bytes streamed vs WAL-replay traffic,
#    zero failed writes during the resync and digest convergence both
#    asserted in-run; the second line loads enough fragment bulk that
#    the stream phase dominates.
run BENCH_CONFIG=resync
run BENCH_CONFIG=resync BENCH_RESYNC_WRITES=8000 BENCH_BATCH=16
# 14) Partitioned replica groups: write QPS through one shard vs two
#    (the slice space split across groups, each with its own sequencer
#    space; scaling_1s_to_2s asserted >= 1.5 in-run, needs >= 3 cores)
#    plus a LIVE RESHARD splitting the hot range under concurrent write
#    load — zero failed writes and digest convergence (moved slices only
#    on the new group) asserted in-run.  The second line runs longer
#    phases with more clients for a stabler ratio.
run BENCH_CONFIG=shard
run BENCH_CONFIG=shard BENCH_THREADS=24 BENCH_SHARD_SECS=10
# 15) Device-first bulk build vs streamed ingest: the SAME seeded pairs
#    through both doors over HTTP (>= 5x pairs/s, digest-identical
#    fragments, and a byte-identical arrow export -> re-ingest round
#    trip all asserted in-run).  The second line sizes a wider slice
#    span so the per-slice commit and egress paths dominate the sort.
run BENCH_CONFIG=bulk
run BENCH_CONFIG=bulk BENCH_BULK_PAIRS=4000000 BENCH_BULK_SLICES=16 BENCH_BULK_ROWS=256
# 16) Cost-based adaptive planner: three query shapes, ground-truth
#    lanes from pinned runs, >= 90% of post-warmup dispatches on the
#    empirically fastest lane asserted in-run; BENCH_STRICT=1 also
#    asserts the mixed-schedule p50 within 5% of the best pinned
#    static.  The second line runs the strict gate with longer phases.
run BENCH_CONFIG=planner
run BENCH_CONFIG=planner BENCH_STRICT=1 BENCH_ITERS=48 BENCH_QUERY_POOL=6
echo "ALL DONE $(date +%H:%M:%S)" >> $OUT
