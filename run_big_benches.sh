#!/bin/bash
# One-off round-2 big-shape bench runs (slow: ~8-17 GB uploads through the
# ~9 MB/s tunnel). Results append to big_bench_results.jsonl.
set -u
cd /root/repo
OUT=big_bench_results.jsonl
run() {
  echo "=== $* $(date +%H:%M:%S)" >> $OUT
  timeout 7200 env "$@" python bench.py >> $OUT 2>>big_bench_errors.log
  echo "--- exit=$? $(date +%H:%M:%S)" >> $OUT
}
# 1) >=1B columns resident on one chip (VERDICT round-2 item 1 'Done').
run BENCH_CONFIG=intersect_count BENCH_SLICES=1024 BENCH_ITERS=128 BENCH_TIMED_RUNS=2
# 2) TopN p50 @ 1.01B columns (BASELINE.json metric).
run BENCH_CONFIG=topn_p50 BENCH_ITERS=64
# 3) Gram-ineligible 4k-row gather-kernel headline with bandwidth_util.
run BENCH_CONFIG=intersect_count_4krows BENCH_TIMED_RUNS=3
# 4) Resident-kernel bandwidth_util at the classic 16-slice shape.
run BENCH_CONFIG=intersect_count PILOSA_TPU_NO_GRAM=1 BENCH_ITERS=512 BENCH_TIMED_RUNS=3
# 5) Bigger-than-HBM stream (17 GB/pass; upload-bound through the tunnel).
run BENCH_CONFIG=intersect_count_stream BENCH_TIMED_RUNS=1 BENCH_ITERS=32
echo "ALL DONE $(date +%H:%M:%S)" >> $OUT
