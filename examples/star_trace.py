"""Star Trace walkthrough — the reference's getting-started demo, end to end.

Models GitHub stargazers: an index over repositories (columns) with a
``stargazer`` frame (rows = users) and a ``language`` frame (rows =
language ids).  Mirrors the PQL sequence from the reference docs: SetBit
writes, Bitmap/Intersect/Union/Count reads, TopN ranking, and row
attributes — driven through a real HTTP server + client.

Run: python examples/star_trace.py          (uses an ephemeral port)
"""

from __future__ import annotations

import os
import socket
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from pilosa_tpu.config import Config
from pilosa_tpu.server.client import Client
from pilosa_tpu.server.server import Server


def main() -> None:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        host = f"127.0.0.1:{s.getsockname()[1]}"
    with tempfile.TemporaryDirectory() as data_dir:
        server = Server(Config(data_dir=data_dir, host=host))
        server.open()
        try:
            c = Client(host)

            # Schema: repository index, stargazer + language frames.
            c.create_index("repository", {"columnLabel": "repo_id"})
            c.create_frame("repository", "stargazer", {"rowLabel": "user_id", "cacheType": "ranked"})
            c.create_frame("repository", "language", {"rowLabel": "language_id"})

            # Load: who starred what, and what language each repo is.
            rng = np.random.default_rng(7)
            stars = [(u, r) for u in range(1, 9) for r in rng.choice(100, size=12, replace=False)]
            c.import_bits("repository", "stargazer", stars)
            langs = [(int(r % 5), int(r)) for r in range(100)]
            c.import_bits("repository", "language", langs)

            # Which repos did user 1 star?
            r = c.execute_query("repository", "Bitmap(user_id=1, frame=stargazer)")
            print("user 1 starred:", r["results"][0]["bitmap"]["bits"][:10], "...")

            # Repos starred by BOTH user 1 and user 2 (the headline shape).
            r = c.execute_query(
                "repository",
                "Count(Intersect(Bitmap(user_id=1, frame=stargazer), Bitmap(user_id=2, frame=stargazer)))",
            )
            print("starred by 1 AND 2:", r["results"][0]["n"])

            # Starred by 1 or 2, written in language 0.
            r = c.execute_query(
                "repository",
                "Count(Intersect(Union(Bitmap(user_id=1, frame=stargazer),"
                " Bitmap(user_id=2, frame=stargazer)), Bitmap(language_id=0, frame=language)))",
            )
            print("(1 OR 2) AND language 0:", r["results"][0]["n"])

            # Top stargazers (ranked cache + two-phase exact counts).
            r = c.execute_query("repository", "TopN(frame=stargazer, n=3)")
            print("top stargazers:", [(p["id"], p["count"]) for p in r["results"][0]["pairs"]])

            # Row attributes ride along with Bitmap results.
            c.execute_query("repository", 'SetRowAttrs(user_id=1, frame=stargazer, name="alice")')
            r = c.execute_query("repository", "Bitmap(user_id=1, frame=stargazer)")
            print("user 1 attrs:", r["results"][0]["bitmap"]["attrs"])

            # Time-quantum views: stars carry timestamps, Range unions the
            # minimal view cover; a batch of Count(Range) calls fuses into
            # one multi-view kernel dispatch with a cover memo.
            c.create_frame(
                "repository", "stargazer_t",
                {"rowLabel": "user_id", "timeQuantum": "YMD"},
            )
            c.execute_query(
                "repository",
                'SetBit(user_id=1, frame="stargazer_t", repo_id=10, timestamp="2017-03-02T00:00") '
                'SetBit(user_id=1, frame="stargazer_t", repo_id=20, timestamp="2017-06-15T00:00") '
                'SetBit(user_id=2, frame="stargazer_t", repo_id=10, timestamp="2017-03-05T00:00")',
            )
            r = c.execute_query(
                "repository",
                'Count(Range(user_id=1, frame="stargazer_t", start="2017-03-01T00:00", end="2017-04-01T00:00")) '
                'Count(Range(user_id=1, frame="stargazer_t", start="2017-01-01T00:00", end="2018-01-01T00:00")) '
                'Count(Range(user_id=2, frame="stargazer_t", start="2017-03-01T00:00", end="2017-04-01T00:00"))',
            )
            # proto3 omits zero-valued fields: a zero count decodes as {}.
            counts = [res.get("n", 0) for res in r["results"]]
            print("stars in March / all 2017 / user 2 March:", counts)
            assert counts == [1, 2, 1]

            # A batched dashboard request: many pair + 3-way counts in one
            # POST — the executor fuses them into grouped kernel dispatches.
            batch = " ".join(
                f"Count(Intersect(Bitmap(user_id={u}, frame=stargazer),"
                f" Bitmap(user_id={v}, frame=stargazer)))"
                for u, v in [(1, 2), (3, 4), (5, 6), (7, 8)]
            ) + (
                " Count(Intersect(Bitmap(user_id=1, frame=stargazer),"
                " Bitmap(user_id=2, frame=stargazer),"
                " Bitmap(user_id=3, frame=stargazer)))"
            )
            r = c.execute_query("repository", batch)
            print("fused dashboard batch:", [res.get("n", 0) for res in r["results"]])
        finally:
            server.close()


if __name__ == "__main__":
    main()
