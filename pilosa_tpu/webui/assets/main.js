/* pilosa-tpu console (reference webui/assets/main.js analog, written for
 * this framework's JSON API: /version /schema /status /hosts /index/{i}/query). */
"use strict";

const $ = (id) => document.getElementById(id);

// -- tabs -------------------------------------------------------------------

const TABS = ["console", "cluster", "schema"];
TABS.forEach((name) => {
  $("tab-" + name).addEventListener("click", () => {
    TABS.forEach((t) => {
      $("tab-" + t).classList.toggle("active", t === name);
      $("pane-" + t).classList.toggle("active", t === name);
    });
    if (name === "cluster") loadCluster();
    if (name === "schema") loadSchema();
  });
});

// -- bootstrap --------------------------------------------------------------

async function getJSON(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(await r.text());
  return r.json();
}

async function loadVersion() {
  try {
    const v = await getJSON("/version");
    $("version").textContent = "v" + v.version;
  } catch (e) {
    $("version").textContent = "";
  }
}

async function loadIndexes() {
  const sel = $("index-select");
  const prev = sel.value;
  sel.innerHTML = '<option value="">Select index</option>';
  try {
    const schema = await getJSON("/schema");
    for (const idx of schema.indexes || []) {
      const opt = document.createElement("option");
      opt.value = idx.name;
      opt.textContent = idx.name;
      sel.appendChild(opt);
    }
    sel.value = prev;
  } catch (e) {
    /* server unreachable; leave the placeholder */
  }
}

// -- console ----------------------------------------------------------------

function renderResult(query, body, ms, isError) {
  const div = document.createElement("div");
  div.className = "result" + (isError ? " error" : "");
  const meta = document.createElement("div");
  meta.className = "meta";
  meta.textContent = `${new Date().toLocaleTimeString()}  ${ms.toFixed(1)} ms  ${query}`;
  const pre = document.createElement("div");
  pre.textContent = body;
  div.appendChild(meta);
  div.appendChild(pre);
  $("output").prepend(div);
  while ($("output").childElementCount > 50) $("output").lastChild.remove();
}

async function runQuery() {
  const index = $("index-select").value;
  const query = $("query").value.trim();
  if (!index) return renderResult(query, "select an index first", 0, true);
  if (!query) return;
  const t0 = performance.now();
  try {
    const r = await fetch(`/index/${encodeURIComponent(index)}/query`, {
      method: "POST",
      body: query,
    });
    const text = await r.text();
    const ms = performance.now() - t0;
    $("timing").textContent = ms.toFixed(1) + " ms";
    let pretty = text;
    try {
      pretty = JSON.stringify(JSON.parse(text), null, 2);
    } catch (e) {
      /* leave as-is */
    }
    renderResult(query, pretty, ms, !r.ok);
    if (/^(SetBit|ClearBit|SetRowAttrs|SetColumnAttrs)/.test(query)) loadIndexes();
  } catch (e) {
    renderResult(query, String(e), performance.now() - t0, true);
  }
}

// Query history: Up/Down recall (persisted), like a shell prompt.
const HISTORY_KEY = "pilosa-tpu-history";
let history = [];
try {
  history = JSON.parse(localStorage.getItem(HISTORY_KEY) || "[]");
} catch (e) {
  history = [];
}
let histPos = history.length; // one past the end = "editing a new query"
let histDraft = "";

function pushHistory(q) {
  if (!q || history[history.length - 1] === q) {
    histPos = history.length;
    return;
  }
  history.push(q);
  if (history.length > 100) history = history.slice(-100);
  histPos = history.length;
  try {
    localStorage.setItem(HISTORY_KEY, JSON.stringify(history));
  } catch (e) {
    /* private mode */
  }
}

// Keyword autocomplete: Tab completes the word before the caret against
// the PQL call names and common argument keys; repeated Tab cycles.
const KEYWORDS = [
  "Bitmap(", "Count(", "Intersect(", "Union(", "Difference(", "Xor(",
  "Range(", "TopN(", "SetBit(", "ClearBit(", "SetRowAttrs(",
  "SetColumnAttrs(",
  "rowID=", "columnID=", "frame=", "n=", "field=", "filters=",
  "timestamp=", "start=", "end=", "tanimotoThreshold=", "threshold=",
  "inverse=",
];
let tabMatches = [];
let tabIndex = 0;
let tabStart = -1;

function completeAt(el) {
  const pos = el.selectionStart;
  // Only cycle when the caret still sits right after the previous
  // completion; any other caret position starts a fresh completion.
  const cycling =
    tabMatches.length &&
    tabStart >= 0 &&
    pos === tabStart + tabMatches[tabIndex].length;
  if (cycling) {
    // cycle: replace the previous completion with the next candidate
    tabIndex = (tabIndex + 1) % tabMatches.length;
  } else {
    tabMatches = [];
    tabStart = -1;
    const before = el.value.slice(0, pos);
    const m = before.match(/[A-Za-z]+$/);
    if (!m) return;
    tabStart = pos - m[0].length;
    const word = m[0].toLowerCase();
    tabMatches = KEYWORDS.filter((k) => k.toLowerCase().startsWith(word));
    tabIndex = 0;
    if (!tabMatches.length) {
      tabStart = -1;
      return;
    }
  }
  const cand = tabMatches[tabIndex];
  el.value = el.value.slice(0, tabStart) + cand + el.value.slice(el.selectionStart);
  const caret = tabStart + cand.length;
  el.setSelectionRange(caret, caret);
}

$("run").addEventListener("click", () => {
  pushHistory($("query").value.trim());
  runQuery();
});
$("query").addEventListener("keydown", (ev) => {
  const el = ev.target;
  if ((ev.ctrlKey || ev.metaKey) && ev.key === "Enter") {
    pushHistory(el.value.trim());
    runQuery();
    return;
  }
  if (ev.key === "Tab" && !ev.shiftKey) {
    ev.preventDefault();
    completeAt(el);
    return;
  }
  tabMatches = [];
  tabStart = -1;
  // History only when the caret is on the first/last line (multiline
  // editing keeps normal cursor movement).
  if (ev.key === "ArrowUp" && !el.value.slice(0, el.selectionStart).includes("\n")) {
    if (histPos > 0) {
      if (histPos === history.length) histDraft = el.value;
      histPos -= 1;
      el.value = history[histPos];
      ev.preventDefault();
    }
  } else if (ev.key === "ArrowDown" && !el.value.slice(el.selectionEnd).includes("\n")) {
    if (histPos < history.length) {
      histPos += 1;
      el.value = histPos === history.length ? histDraft : history[histPos];
      ev.preventDefault();
    }
  }
});

// -- cluster ----------------------------------------------------------------

async function loadCluster() {
  const tbody = $("cluster-table").querySelector("tbody");
  tbody.innerHTML = "";
  try {
    const status = await getJSON("/status");
    for (const node of status.status?.cluster?.nodes || []) {
      // Hosts arrive over the unauthenticated gossip channel — render as
      // text, never markup.
      const tr = document.createElement("tr");
      const state = node.state || "UP";
      for (const text of [node.host, node.internalHost || "", state]) {
        const td = document.createElement("td");
        td.textContent = text;
        tr.appendChild(td);
      }
      tr.lastChild.className = `state-${state === "DOWN" ? "DOWN" : "UP"}`;
      tbody.appendChild(tr);
    }
  } catch (e) {
    const tr = document.createElement("tr");
    const td = document.createElement("td");
    td.colSpan = 3;
    td.textContent = String(e);
    tr.appendChild(td);
    tbody.appendChild(tr);
  }
}

// -- schema -----------------------------------------------------------------

async function loadSchema() {
  const tree = $("schema-tree");
  tree.innerHTML = "";
  try {
    const schema = await getJSON("/schema");
    for (const idx of schema.indexes || []) {
      const div = document.createElement("div");
      div.className = "tree-index";
      const name = document.createElement("div");
      name.className = "name";
      name.textContent = idx.name;
      div.appendChild(name);
      for (const fr of idx.frames || []) {
        const fdiv = document.createElement("div");
        fdiv.className = "tree-frame";
        const opts = [];
        if (fr.rowLabel) opts.push("rowLabel=" + fr.rowLabel);
        if (fr.cacheType) opts.push("cache=" + fr.cacheType + ":" + fr.cacheSize);
        if (fr.timeQuantum) opts.push("time=" + fr.timeQuantum);
        if (fr.inverseEnabled) opts.push("inverse");
        fdiv.innerHTML = `${fr.name} <span class="opts">${opts.join("  ")}</span>`;
        div.appendChild(fdiv);
      }
      tree.appendChild(div);
    }
    if (!tree.childElementCount) tree.textContent = "no indexes";
  } catch (e) {
    tree.textContent = String(e);
  }
}

loadVersion();
loadIndexes();
