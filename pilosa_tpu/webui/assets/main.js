/* pilosa-tpu console (reference webui/assets/main.js analog, written for
 * this framework's JSON API: /version /schema /status /hosts /index/{i}/query). */
"use strict";

const $ = (id) => document.getElementById(id);

// -- tabs -------------------------------------------------------------------

const TABS = ["console", "cluster", "schema"];
TABS.forEach((name) => {
  $("tab-" + name).addEventListener("click", () => {
    TABS.forEach((t) => {
      $("tab-" + t).classList.toggle("active", t === name);
      $("pane-" + t).classList.toggle("active", t === name);
    });
    if (name === "cluster") loadCluster();
    if (name === "schema") loadSchema();
  });
});

// -- bootstrap --------------------------------------------------------------

async function getJSON(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(await r.text());
  return r.json();
}

async function loadVersion() {
  try {
    const v = await getJSON("/version");
    $("version").textContent = "v" + v.version;
  } catch (e) {
    $("version").textContent = "";
  }
}

async function loadIndexes() {
  const sel = $("index-select");
  const prev = sel.value;
  sel.innerHTML = '<option value="">Select index</option>';
  try {
    const schema = await getJSON("/schema");
    for (const idx of schema.indexes || []) {
      const opt = document.createElement("option");
      opt.value = idx.name;
      opt.textContent = idx.name;
      sel.appendChild(opt);
    }
    sel.value = prev;
  } catch (e) {
    /* server unreachable; leave the placeholder */
  }
}

// -- console ----------------------------------------------------------------

function renderResult(query, body, ms, isError) {
  const div = document.createElement("div");
  div.className = "result" + (isError ? " error" : "");
  const meta = document.createElement("div");
  meta.className = "meta";
  meta.textContent = `${new Date().toLocaleTimeString()}  ${ms.toFixed(1)} ms  ${query}`;
  const pre = document.createElement("div");
  pre.textContent = body;
  div.appendChild(meta);
  div.appendChild(pre);
  $("output").prepend(div);
  while ($("output").childElementCount > 50) $("output").lastChild.remove();
}

async function runQuery() {
  const index = $("index-select").value;
  const query = $("query").value.trim();
  if (!index) return renderResult(query, "select an index first", 0, true);
  if (!query) return;
  const t0 = performance.now();
  try {
    const r = await fetch(`/index/${encodeURIComponent(index)}/query`, {
      method: "POST",
      body: query,
    });
    const text = await r.text();
    const ms = performance.now() - t0;
    $("timing").textContent = ms.toFixed(1) + " ms";
    let pretty = text;
    try {
      pretty = JSON.stringify(JSON.parse(text), null, 2);
    } catch (e) {
      /* leave as-is */
    }
    renderResult(query, pretty, ms, !r.ok);
    if (/^(SetBit|ClearBit|SetRowAttrs|SetColumnAttrs)/.test(query)) loadIndexes();
  } catch (e) {
    renderResult(query, String(e), performance.now() - t0, true);
  }
}

$("run").addEventListener("click", runQuery);
$("query").addEventListener("keydown", (ev) => {
  if ((ev.ctrlKey || ev.metaKey) && ev.key === "Enter") runQuery();
});

// -- cluster ----------------------------------------------------------------

async function loadCluster() {
  const tbody = $("cluster-table").querySelector("tbody");
  tbody.innerHTML = "";
  try {
    const status = await getJSON("/status");
    for (const node of status.status?.cluster?.nodes || []) {
      // Hosts arrive over the unauthenticated gossip channel — render as
      // text, never markup.
      const tr = document.createElement("tr");
      const state = node.state || "UP";
      for (const text of [node.host, node.internalHost || "", state]) {
        const td = document.createElement("td");
        td.textContent = text;
        tr.appendChild(td);
      }
      tr.lastChild.className = `state-${state === "DOWN" ? "DOWN" : "UP"}`;
      tbody.appendChild(tr);
    }
  } catch (e) {
    const tr = document.createElement("tr");
    const td = document.createElement("td");
    td.colSpan = 3;
    td.textContent = String(e);
    tr.appendChild(td);
    tbody.appendChild(tr);
  }
}

// -- schema -----------------------------------------------------------------

async function loadSchema() {
  const tree = $("schema-tree");
  tree.innerHTML = "";
  try {
    const schema = await getJSON("/schema");
    for (const idx of schema.indexes || []) {
      const div = document.createElement("div");
      div.className = "tree-index";
      const name = document.createElement("div");
      name.className = "name";
      name.textContent = idx.name;
      div.appendChild(name);
      for (const fr of idx.frames || []) {
        const fdiv = document.createElement("div");
        fdiv.className = "tree-frame";
        const opts = [];
        if (fr.rowLabel) opts.push("rowLabel=" + fr.rowLabel);
        if (fr.cacheType) opts.push("cache=" + fr.cacheType + ":" + fr.cacheSize);
        if (fr.timeQuantum) opts.push("time=" + fr.timeQuantum);
        if (fr.inverseEnabled) opts.push("inverse");
        fdiv.innerHTML = `${fr.name} <span class="opts">${opts.join("  ")}</span>`;
        div.appendChild(fdiv);
      }
      tree.appendChild(div);
    }
    if (!tree.childElementCount) tree.textContent = "no indexes";
  } catch (e) {
    tree.textContent = String(e);
  }
}

loadVersion();
loadIndexes();
