"""Distribution: slice-axis sharding over a device mesh + cluster placement.

Reference analog: the scatter-gather half of executor.go (mapReduce,
executor.go:1115-1244) and cluster.go.  Inside one host/pod, the
goroutine-per-slice fan-out becomes GSPMD: bitmap stacks are sharded along
a ``slice`` mesh axis and XLA inserts the ICI collectives (psum for Count,
all_gather for bitmap materialization, top-k merge for TopN).  Across
hosts there are two planes: a GLOBAL jax.distributed mesh whose
collectives ride ICI/DCN (multihost.py — homogeneous TPU jobs), and the
hash-ring + HTTP-forwarded remote execution mirroring the reference's
data plane (pilosa_tpu.cluster — heterogeneous clusters).
"""

from pilosa_tpu.parallel.multihost import (  # noqa: F401
    MultiHostReplicaMesh,
    MultiHostSliceMesh,
    init_multihost,
)
from pilosa_tpu.parallel.sharded import (  # noqa: F401
    ReplicaMesh,
    SliceMesh,
    replica_gather_count,
    sharded_count_and,
    sharded_count_call,
    sharded_union_reduce,
)


def __getattr__(name):
    # PEP 562 lazy export: service.py transitively imports jax (executor,
    # kernels, server stack), and this package must stay importable on
    # numpy-only hosts — same contract as pilosa_tpu/__init__.py.
    if name == "LockstepService":
        from pilosa_tpu.parallel.service import LockstepService

        return LockstepService
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
