"""Multi-host slice meshes: one GLOBAL device mesh spanning processes.

The reference scales past one node with an HTTP+protobuf data plane and a
hash ring (cluster.go, executor.go:1009-1091).  That path survives here
for heterogeneous clusters (pilosa_tpu/cluster.py), but homogeneous TPU
pods get the TPU-native alternative: every host joins one
``jax.distributed`` job, the slice axis shards over the GLOBAL device
list, and XLA emits the cross-host collectives — psum riding ICI within
a pod slice and DCN between pods — where the reference serialized
protobuf over TCP.  The coordinator/worker topology mirrors the
reference's cluster config (a coordinator address + a static host list,
config.go:37-64); there is no gossip because membership is the jax
distributed runtime's job.

All SliceMesh kernels (sharded.py) work unchanged on a multi-host mesh:
they only see a Mesh and globally-sharded arrays.  What this module adds
is the process boundary: initialization, and building global arrays from
process-LOCAL slice shards (each host densifies only the fragments it
owns — the analog of per-node fragment ownership, cluster.go:243-254).

Tested with real multi-process meshes over the gloo CPU backend in
tests/test_multihost.py; on TPU pods ``jax.distributed.initialize()``
discovers the topology from the TPU runtime instead.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from pilosa_tpu.parallel.sharded import ReplicaMesh, SliceMesh, _require_divisible


def init_multihost(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_count: Optional[int] = None,
) -> None:
    """Join this process to a multi-host jax job.

    On TPU pods call with no arguments (topology comes from the runtime).
    On CPU (tests, dev rigs) pass coordinator/num_processes/process_id
    and optionally local_device_count virtual devices per process; the
    gloo collectives backend carries the cross-process reductions.

    Must run before any jax computation initializes a backend.
    """
    import jax

    if local_device_count is not None:
        # Force a CPU backend with N virtual devices even when a TPU
        # plugin latched the platform at import time (same workaround as
        # tests/conftest.py — backends are created lazily).
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", local_device_count)
        except AttributeError:
            # Older jax: the option predates jax_num_cpu_devices — the
            # XLA flag does the same thing and is read at backend init
            # (which hasn't happened yet by this function's contract).
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={local_device_count}"
            ).strip()
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except AttributeError:
            pass  # older jax: gloo is the only distributed CPU choice anyway
    if coordinator is None:
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )


class MultiHostSliceMesh(SliceMesh):
    """SliceMesh over the GLOBAL device list of a jax.distributed job.

    Inherits every kernel-facing behavior; adds construction of global
    slice stacks from per-process local data.  Slice ownership is
    deterministic and contiguous: device k owns slices
    [k*per_dev, (k+1)*per_dev) of the stack, so host ownership is the
    devices it holds — the mesh replaces the reference's
    jump-consistent-hash ring (cluster.go:220-240) inside the job.
    """

    def __init__(self, devices: Sequence | None = None):
        import jax

        super().__init__(devices if devices is not None else jax.devices())
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()

    def _local_device_ranges(self, n_slices: int) -> list[tuple[object, range]]:
        """(local device, owned global slice range) pairs — the ONE place
        the ownership rule lives.  Local devices outside an explicit mesh
        device subset own nothing (skipped, not an error)."""
        import jax

        _require_divisible(n_slices, self.n_devices)
        per_dev = n_slices // self.n_devices
        positions = {d: k for k, d in enumerate(self.mesh.devices.flat)}
        out = []
        for d in jax.local_devices():
            k = positions.get(d)
            if k is not None:
                out.append((d, range(k * per_dev, (k + 1) * per_dev)))
        return out

    def owned_slices(self, n_slices: int) -> list[int]:
        """Global slice indices whose shards live on THIS process."""
        return [s for _, r in self._local_device_ranges(n_slices) for s in r]

    def shard_stack_local(
        self,
        local_data: dict[int, np.ndarray],
        n_slices: int,
        row_shape: tuple,
        dtype=np.uint32,
    ):
        """Build a global [n_slices, *row_shape] array from THIS process's
        slices only (missing owned slices are zero).

        ``local_data`` maps global slice index -> np.ndarray of
        ``row_shape``; only slices owned by this process are consulted.
        No host ever materializes the full stack — the multi-host analog
        of each node opening only its own fragments (holder.go:73-121).
        """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(self.AXIS, *([None] * len(row_shape)))
        sharding = NamedSharding(self.mesh, spec)
        # dtype is an explicit parameter (not inferred from local_data): a
        # host owning only empty slices must still agree with its peers on
        # the global aval, or cross-process collectives fail.
        for v in local_data.values():
            if v.dtype != dtype:
                raise TypeError(f"local slice dtype {v.dtype} != declared {np.dtype(dtype)}")
        shards = []
        for d, owned in self._local_device_ranges(n_slices):
            block = np.zeros((len(owned), *row_shape), dtype=dtype)
            for j, s in enumerate(owned):
                if s in local_data:
                    block[j] = local_data[s]
            shards.append(jax.device_put(block, d))
        return jax.make_array_from_single_device_arrays(
            (n_slices, *row_shape), sharding, shards
        )

    def fetch_global(self, arr) -> np.ndarray:
        """Gather a globally-sharded array to every host (DCN all-gather;
        the analog of streaming result segments back to the coordinator)."""
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


class MultiHostReplicaMesh(ReplicaMesh):
    """2-D (slice x replica) mesh over the GLOBAL device list of a
    ``jax.distributed`` job — the device plane of one replicated serving
    group at pod scale.

    ``hybrid`` defaults to True: the replica axis is laid across DCN
    granules (``mesh_utils.create_hybrid_device_mesh``) so every
    slice-axis psum stays on ICI inside a pod and only cross-replica
    traffic crosses DCN — the multi-pod layout BACKLOG.md prescribes.
    ReplicaMesh's guarded fallback keeps construction working on dev
    rigs without a DCN topology (gloo CPU jobs), so the same code path
    is testable with multi-process CPU meshes.

    Adds the process-boundary helpers the serving path needs: which
    replica column this process's devices sit in, and which global
    slices it owns WITHIN that column (the 2-D analog of
    MultiHostSliceMesh's contiguous ownership rule).
    """

    def __init__(self, n_replicas: int = 2, devices: Sequence | None = None,
                 hybrid: bool = True):
        import jax

        super().__init__(
            n_replicas=n_replicas,
            devices=devices if devices is not None else jax.devices(),
            hybrid=hybrid,
        )
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()

    def _local_positions(self) -> list[tuple[int, int]]:
        """(slice row, replica column) of every local device in the
        mesh.  Local devices outside an explicit device subset own
        nothing (skipped, not an error) — the SliceMesh rule in 2-D."""
        import jax

        pos = {d: (int(r), int(c))
               for (r, c), d in np.ndenumerate(self.mesh.devices)}
        return [pos[d] for d in jax.local_devices() if d in pos]

    def local_replica_groups(self) -> list[int]:
        """Replica columns this process participates in.  A well-formed
        hybrid layout keeps each process inside ONE column (its pod);
        flat CPU fallbacks may straddle several."""
        return sorted({c for _, c in self._local_positions()})

    def owned_slices(self, n_slices: int) -> list[int]:
        """Global slice indices whose shards live on THIS process (in
        any replica column it holds — each column is a full copy, so
        ownership is per (row, column) device)."""
        _require_divisible(n_slices, self.n_devices)
        per_dev = n_slices // self.n_devices
        out = set()
        for r, _c in self._local_positions():
            out.update(range(r * per_dev, (r + 1) * per_dev))
        return sorted(out)
