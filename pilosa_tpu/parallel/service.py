"""Lockstep multi-host query service: one HTTP front end, SPMD execution.

The reference serves distributed queries coordinator-style: the handler
node parses, fans slice batches out to peers over HTTP+protobuf, and
reduces (executor.go:1009-1244).  On a homogeneous TPU job the
TPU-native alternative is SPMD LOCKSTEP: every process holds the same
holder data, joins one ``jax.distributed`` mesh, and executes the SAME
query program; device work is sharded over the global slice axis and
XLA's collectives (psum over ICI/DCN) do the reduce that protobuf
responses did in the reference.

This module is the SERVICE shell around that execution model
(tests/test_multihost.py proves the execution model itself):

- rank 0 runs the HTTP front end (``POST /index/<name>/query``, the
  reference's wire shape, handler.go:179-243) and a control-plane TCP
  listener;
- every other rank connects to the control plane and replays, in
  arrival order, exactly the requests rank 0 serves;
- rank 0 forwards each request to all ranks BEFORE executing it
  locally, so every process enters the same jitted computations in the
  same order — the lockstep invariant the collectives require.

Requests flow through ONE total order — a sequence number assigned on
rank 0 — but execution is PIPELINED: N requests can be in flight on the
control plane (sends, receipt acks) while device execution proceeds
strictly in sequence order on every rank, so concurrent HTTP clients
overlap their network/parse time with each other's device time without
ever breaking the lockstep invariant.  Writes (SetBit etc.) replay
identically on every rank, keeping the replicated holders convergent.
Errors raised before device work (parse errors, unknown frames) raise
identically everywhere — rank 0 reports them to the client, workers log
and continue.

COALESCING: concurrent requests drain into ONE control-plane batch
entry (``{"op": "batch", "seq": n, "reqs": [{"index", "query"}, ...]}``)
through the same rotating-leader group commit the ingest queue uses —
one sequence number, one fan-out send, and one ack round per batch
instead of per request, amortizing the fixed replay overhead across the
batch.  Every rank executes the batch's requests in list order inside
the batch's slot in the total order, so the lockstep invariant is
untouched; per-request errors are ISOLATED (a deterministic PilosaError
is returned to its own client and skipped identically on every rank —
it never poisons sibling requests or desynchronizes ranks).
``PILOSA_TPU_LOCKSTEP_COALESCE`` caps the batch size (default 32;
1 disables coalescing).  An idle service adds no latency: the first
request leads immediately and ships a batch of one.

QoS: each request may carry a deadline (``X-Pilosa-Deadline-Ms``
header, or the service's ``default_deadline_ms``).  Expiry is decided
ONCE — on rank 0, at ship time — and rides the batch entry as a
per-request ``expired`` flag (plus ``deadline_ms`` remaining, for
observability): every rank drops the same expired requests before
execution from the flag alone, so no clock sync is assumed and the
lockstep invariant holds (the client gets a 504).  The arrival queue
is bounded (``queue_depth``, default 256): a request landing on a full
queue gets 429 + Retry-After at the door, and a degraded control plane
answers 503 + Retry-After instead of 400.

TRACING: the head-sampling decision for the request tracer
(``PILOSA_TPU_TRACE_SAMPLE_RATE`` / ``_SLOW_MS``, or ctor args from the
CLI's [trace] config) is decided ONCE — on rank 0 at ship time, forced
by an inbound ``X-Pilosa-Trace`` header — and rides the batch entry as
a per-request ``trace`` flag, exactly like expiry: every rank reads
the flag (never its own RNG), so the decision is identical everywhere.
Tracing never changes execution, so workers only COUNT the flags
(``stat_traced``, the determinism probe); rank 0 additionally records
each traced request's queue/ship/execute phases (the ship span covers
the worker fan-out + receipt-ack barrier) into its tracer ring, served
at ``/debug/traces`` by the full server or read off ``svc.tracer``.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import sys
import threading

from pilosa_tpu.analysis import lockcheck
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

_now = time.perf_counter

from pilosa_tpu.engine import MeshEngine
from pilosa_tpu.executor import ExecOptions, Executor
from pilosa_tpu.pilosa import ErrFrameNotFound, ErrIndexNotFound, PilosaError
from pilosa_tpu.qos import DeadlineExceeded, ShedError, deadline_from_headers
from pilosa_tpu.server.handler import result_to_json

_LEN = struct.Struct("<I")

# Reserved internal entry for the streaming-ingest completion hook: the
# front end ships it through the normal total order and EVERY rank
# executes the rank-cache recalculation identically (import parity).
# The NUL bytes keep it outside any parseable PQL; a client posting the
# sentinel directly just triggers a harmless recalc.
INGEST_RECALC_PREFIX = "\x00ingest-recalc\x00"

# Reserved internal entries for the device-build bulk door: rank 0
# decodes each chunk once and replays the decoded pairs through the
# total order as base64(packed-uint64) bodies — every rank runs the
# SAME build kernel over the SAME pairs, so the committed plane
# overlays are replicated without rank-0 shipping any derived state.
# The recalc sentinel runs the completion hook (rank-cache recalc +
# budgeted materialization) identically on every rank.
BULK_APPLY_PREFIX = "\x00bulk-apply\x00"
BULK_RECALC_PREFIX = "\x00bulk-recalc\x00"


class DegradedError(PilosaError):
    """The lockstep control plane lost a rank — the replicas can no
    longer be guaranteed identical, so the whole service refuses work
    (HTTP 503 + Retry-After: clients should come back to a RESTARTED
    job, not hammer a dead one)."""

    retry_after = 5.0


def _send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode("utf-8")
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        if not chunk:
            return None
        head += chunk
    (n,) = _LEN.unpack(head)
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            return None
        data += chunk
    return json.loads(data.decode("utf-8"))


class LockstepService:
    """SPMD query service over a joined ``jax.distributed`` job.

    Construct AFTER ``init_multihost`` (or ``jax.distributed.initialize``)
    on every process, with identical holder contents, then call
    :meth:`serve_forever`.  Rank 0 needs ``http_addr`` and
    ``control_addr``; workers need the same ``control_addr`` to connect.
    """

    def __init__(
        self,
        holder,
        control_addr: tuple[str, int],
        http_addr: Optional[tuple[str, int]] = None,
        devices=None,
        ack_timeout: Optional[float] = None,
        connect_timeout: Optional[float] = None,
        queue_depth: Optional[int] = None,
        default_deadline_ms: Optional[float] = None,
        qcache_enabled: Optional[bool] = None,
        qcache_max_bytes: Optional[int] = None,
        trace_sample_rate: Optional[float] = None,
        trace_slow_ms: Optional[float] = None,
        group: Optional[str] = None,
        group_epoch: Optional[int] = None,
        bulk_batch_slices: Optional[int] = None,
        bulk_materialize_budget_ms: Optional[float] = None,
        tenancy_map: Optional[str] = None,
    ):
        import jax

        from pilosa_tpu import qcache as qcache_mod
        from pilosa_tpu import trace as trace_mod
        from pilosa_tpu.replica import parse_group

        self.holder = holder
        self.rank = jax.process_index()
        self.n_ranks = jax.process_count()
        # GROUP IDENTITY (replica serving groups): this job is one
        # serving group behind the replica router.  The name@epoch pair
        # rides every HTTP response (X-Pilosa-Group — the router's
        # epoch-bump detection) and every control-plane batch entry
        # (``gepoch``): every rank of a group is constructed with the
        # SAME epoch, so a worker receiving an entry from a DIFFERENT
        # epoch is talking to a stale rank 0 from a previous incarnation
        # and fail-stops rather than replaying writes the restarted
        # group never acknowledged.  Ctor args (the CLI passes [replica]
        # config) > PILOSA_TPU_REPLICA_GROUP env ("name[@epoch]") > off.
        if group is None and group_epoch is None:
            group, env_epoch = parse_group(
                os.environ.get("PILOSA_TPU_REPLICA_GROUP", "")  # analysis-ok: env-knob-outside-config: rank-process fallback; ctor args win, ranks inherit the launcher's env
            )
            group_epoch = env_epoch
        self.group = group or ""
        self.group_epoch = int(group_epoch or 0)
        # Replica durability: rank 0 tracks (and persists beside the
        # holder data) the highest router write sequence this group has
        # applied — reported on every response (X-Pilosa-Applied-Seq)
        # and at /replica/health, so a restarted lockstep job tells the
        # router exactly which WAL suffix to replay.  Workers never see
        # HTTP headers; the front end is the single writer.
        from pilosa_tpu.replica.catchup import AppliedSeq

        holder_path = getattr(holder, "path", None)
        self.applied_seq = AppliedSeq(
            os.path.join(holder_path, "applied_seq")
            if (self.group and holder_path and self.rank == 0)
            else None
        )
        self.engine = MeshEngine(devices if devices is not None else jax.devices())
        # Observability plane: a real expvar registry (rank 0 serves it
        # at /debug/vars and /metrics) plus the dispatch meter + cost
        # ledger the full server carries, gated by PILOSA_TPU_COSTS like
        # there.  Stats are rank-local TELEMETRY — never read back into
        # control flow — so recording them on every rank cannot skew the
        # SPMD total order.
        from pilosa_tpu import costs as costs_mod
        from pilosa_tpu.stats import ExpvarStatsClient

        self.stats = ExpvarStatsClient()
        self.costs = (
            costs_mod.CostLedger(stats=self.stats)
            if costs_mod.enabled_from_env()
            else None
        )
        # Query result cache, DETERMINISTIC variant: hit/miss must be a
        # pure function of replicated state (request strings + the
        # lockstep total order of writes), so every rank hits or misses
        # identically and no rank skips a collective another rank runs —
        # the same rule as error isolation and expired-request drops.
        # Wall-clock cost admission is rank-local, so min_cost_ms is
        # FORCED to 0 here (admit every eligible read); byte-accounted
        # eviction stays deterministic because result sizes and the
        # serialized execution order are identical on every rank.
        if qcache_enabled is None:
            qcache_enabled = os.environ.get("PILOSA_TPU_QCACHE", "").lower() in (  # analysis-ok: env-knob-outside-config: rank-process fallback; ctor args win, ranks inherit the launcher's env
                "1", "true", "yes",
            )
        if qcache_max_bytes is None:
            qcache_max_bytes = int(
                os.environ.get(  # analysis-ok: env-knob-outside-config: rank-process fallback; ctor args win, ranks inherit the launcher's env
                    "PILOSA_TPU_QCACHE_MAX_BYTES", str(qcache_mod.DEFAULT_MAX_BYTES)
                )
            )
        qc = (
            qcache_mod.QueryCache(max_bytes=qcache_max_bytes, min_cost_ms=0.0)
            if qcache_enabled
            else None
        )
        self.executor = Executor(
            holder, engine=self.engine, qcache=qc,
            stats=self.stats if self.costs is not None else None,
        )
        # Cost-based planner, RANK 0 ONLY: plans are computed once at
        # ship time and ride the batch wire entry exactly like the
        # expiry and trace flags, so every rank applies rank 0's lane
        # and no rank ever consults rank-local state.  Workers carry
        # planner=None (they read plans off the wire); the EXECUTOR
        # planner is also rank-0-only so the ledger fold-back (wall
        # timestamps, win/loss tallies) stays telemetry, never control
        # flow on a worker.  PILOSA_TPU_PLANNER=0 disables.
        self.planner = None
        if (
            self.rank == 0
            and self.costs is not None
            and os.environ.get("PILOSA_TPU_PLANNER", "").lower()  # analysis-ok: env-knob-outside-config: rank-process fallback; ctor args win, ranks inherit the launcher's env
            not in ("0", "false", "no")
        ):
            from pilosa_tpu import planner as planner_mod

            self.planner = planner_mod.Planner(self.costs, stats=self.stats)
            self.executor.planner = self.planner
        self.control_addr = control_addr
        self.http_addr = http_addr
        self._workers: list[socket.socket] = []
        # Bound on how long rank 0 waits for a worker's receipt ack (and
        # for the send buffer to drain).  Acks come from the workers'
        # reader threads (receipt, not completion), so this only needs to
        # cover control-plane latency plus scheduling hiccups.  Config
        # precedence (PR-2 style): ctor arg (the CLI passes
        # Config.lockstep_ack_timeout) > env > default.
        if ack_timeout is None:
            ack_timeout = float(os.environ.get("PILOSA_TPU_LOCKSTEP_ACK_TIMEOUT", "120"))  # analysis-ok: env-knob-outside-config: rank-process fallback; ctor args win, ranks inherit the launcher's env
        self.ack_timeout = ack_timeout
        # Worker startup: how long a worker retries connecting to rank
        # 0's control listener (the gossip seed-join startup race).
        if connect_timeout is None:
            connect_timeout = float(
                os.environ.get("PILOSA_TPU_LOCKSTEP_CONNECT_TIMEOUT", "60")  # analysis-ok: env-knob-outside-config: rank-process fallback; ctor args win, ranks inherit the launcher's env
            )
        self.connect_timeout = connect_timeout
        # Admission bound on rank 0's arrival queue: requests beyond
        # this shed with 429 + Retry-After instead of growing the
        # coalescing queue without limit (coalesced batches stay sized,
        # and waiting clients aren't promised work the job can't do).
        # 0 = unbounded.
        if queue_depth is None:
            queue_depth = int(os.environ.get("PILOSA_TPU_LOCKSTEP_QUEUE_DEPTH", "256"))  # analysis-ok: env-knob-outside-config: rank-process fallback; ctor args win, ranks inherit the launcher's env
        self.queue_depth = queue_depth
        # Default per-request budget when no X-Pilosa-Deadline-Ms header
        # arrives; 0 = unbounded.
        if default_deadline_ms is None:
            default_deadline_ms = float(os.environ.get("PILOSA_TPU_DEADLINE_MS", "0"))  # analysis-ok: env-knob-outside-config: rank-process fallback; ctor args win, ranks inherit the launcher's env
        self.default_deadline_ms = default_deadline_ms
        # Request tracer: the sampling decision is made on rank 0 at
        # ship time and rides the batch entry as a per-request flag —
        # every rank reads the flag, never its own RNG, so the decision
        # is replicated (same rule as expiry).  Only rank 0 records
        # spans; workers count the flags (stat_traced).  Ctor args (the
        # CLI passes [trace] config) > env > off.
        if trace_sample_rate is None and trace_slow_ms is None:
            self.tracer = trace_mod.from_env(stats=self.stats, costs=self.costs)
        else:
            rate = trace_sample_rate if trace_sample_rate is not None else 0.0
            slow = trace_slow_ms if trace_slow_ms is not None else 0.0
            self.tracer = (
                trace_mod.Tracer(sample_rate=rate, slow_ms=slow,
                                 stats=self.stats, costs=self.costs)
                if (rate > 0 or slow > 0)
                else None
            )
        # PIPELINED total order: _order_mu only covers sequence assignment
        # + the worker sends (cheap), so N requests can be in flight on
        # the control plane at once; local execution is serialized in
        # sequence order by the _exec_cv gate, matching the workers'
        # socket-order replay.  _ack_mu[i]/_acked[i] track each worker's
        # ordered receipt-ack stream.
        self._order_mu = lockcheck.named_lock("lockstep._order_mu")
        self._next_seq = 1
        self._exec_cv = lockcheck.named_condition("lockstep._exec_cv")
        self._exec_next = 1
        self._ack_mu: list[threading.Lock] = []
        self._acked: list[int] = []
        self._degraded = False
        self._httpd = None
        self._stop = threading.Event()
        # Request coalescing: concurrent _execute callers drain into one
        # control-plane batch entry via a rotating shipper (the ingest
        # WriteQueue's leaderless group commit, SPLIT so shipping and
        # execution pipeline: the shipper releases its role right after
        # the ack round, letting the next batch's forward/ack network
        # time overlap this batch's device execution).  No dedicated
        # thread, no idle timer — a lone request ships immediately as a
        # batch of one.
        self.coalesce_max = max(
            1, int(os.environ.get("PILOSA_TPU_LOCKSTEP_COALESCE", "32"))
        )
        self._q_cv = lockcheck.named_condition("lockstep._q_cv")
        self._q: list = []  # [((index, query), slot)]
        self._shipping = False
        # Ship-ahead pipeline depth: while batch n executes, at most ONE
        # further batch may ship (its forward/ack overlaps n's device
        # time).  Deeper shipping would drain arrivals into batches of
        # one — requests must ACCUMULATE during execution for the
        # coalescing to form real batches.
        self._inflight = 0
        # Telemetry (bench + tests): batches shipped / requests carried,
        # plus QoS outcomes (shed at the arrival queue, dropped expired).
        self.stat_batches = 0
        self.stat_requests = 0
        self.stat_shed = 0
        self.stat_expired = 0
        # Trace flags observed in executed batch entries: every rank
        # counts the SAME number (the flag rides the wire, decided once
        # on rank 0) — the lockstep determinism probe for sampling.
        self.stat_traced = 0
        # Per-tenant request accounting off the wire entries: the tenant
        # is resolved ONCE on rank 0 at ship time (header > [tenancy]
        # map > index name > "default" — the tenancy.resolve seam) and
        # rides the batch entry like the expired/trace/plan flags, so
        # every rank tallies identical per-tenant counts from the flag
        # alone.  tenant -> {"requests": n, "expired": m}.
        from pilosa_tpu import tenancy as tenancy_mod

        if tenancy_map is None:
            tenancy_map = os.environ.get("PILOSA_TPU_TENANCY_MAP", "")  # analysis-ok: env-knob-outside-config: rank-process fallback; ctor args win, ranks inherit the launcher's env
        self.tenancy_index_map = tenancy_mod.parse_map(tenancy_map)
        self.stat_tenants: dict = {}
        # Streaming columnar ingest on the lockstep front end: chunks
        # decode on rank 0 and replay as canonical batched SetBit
        # bodies through the normal total order (every rank applies
        # them — via the native write lane when armed); the completion
        # hook ships the INGEST_RECALC_PREFIX sentinel so every rank
        # recalculates rank caches identically.  Staging state
        # (offsets, running CRC) is rank-0-local: a restarted job
        # re-streams, which is idempotent.
        from pilosa_tpu import ingest as ingest_mod

        self._ingestor = ingest_mod.StreamIngestor(
            self._ingest_apply, complete=self._ingest_complete,
        )
        # Device-build bulk door: chunks decode on rank 0 and the
        # decoded PAIRS replay through the total order (base64 packed
        # bodies) — every rank runs the build kernel itself, so the
        # plane overlays are a pure function of the replicated pairs.
        # The materialize budget only shapes WHEN each rank folds its
        # overlay into roaring storage (physical representation, not
        # logical content), so wall-clock divergence across ranks is
        # benign.  [bulk] config > PILOSA_TPU_BULK_* env > defaults.
        if bulk_batch_slices is None:
            bulk_batch_slices = int(
                os.environ.get("PILOSA_TPU_BULK_BATCH_SLICES", "8")  # analysis-ok: env-knob-outside-config: rank-process fallback; ctor args win, ranks inherit the launcher's env
            )
        if bulk_materialize_budget_ms is None:
            bulk_materialize_budget_ms = float(
                os.environ.get("PILOSA_TPU_BULK_MATERIALIZE_BUDGET_MS", "0")  # analysis-ok: env-knob-outside-config: rank-process fallback; ctor args win, ranks inherit the launcher's env
            )
        self.bulk_batch_slices = bulk_batch_slices
        self.bulk_materialize_budget_ms = bulk_materialize_budget_ms
        self._bulk_ingestor = ingest_mod.StreamIngestor(
            self._bulk_apply, complete=self._bulk_complete,
        )

    # -- rank 0 ----------------------------------------------------------

    def _accept_workers(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(self.control_addr)
        srv.listen(self.n_ranks)
        self.control_addr = srv.getsockname()
        self._control_srv = srv
        for _ in range(self.n_ranks - 1):
            conn, _ = srv.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._workers.append(conn)
            self._ack_mu.append(lockcheck.named_lock("lockstep._ack_mu"))
            self._acked.append(0)

    def _degrade(self, e) -> "DegradedError":
        self._degraded = True
        with self._exec_cv:
            self._exec_cv.notify_all()
        return DegradedError(
            f"lockstep control plane lost a rank ({e}); "
            "service degraded — restart the job"
        )

    def _await_acks(self, seq: int) -> None:
        """Wait until every worker has acked receipt of request ``seq``.

        Each worker's control socket delivers one ack byte per request in
        order, so "acked seq n" == "n ack bytes consumed"; any thread may
        consume acks for earlier sequences on the way (the per-worker
        lock keeps consumption single-threaded).  A timeout counts as a
        lost rank — detected here instead of by hanging in the collective
        the dead worker will never enter.
        """
        for i, w in enumerate(self._workers):
            with self._ack_mu[i]:
                while self._acked[i] < seq:
                    b = w.recv(1)
                    if b != b"k":
                        raise OSError("worker closed control connection")
                    self._acked[i] += 1

    def _execute(self, index: str, query: str, deadline=None, trace_force=False,
                 tenant_hdr=None):
        """Serve one request through the coalescing queue.

        ADMISSION: the arrival queue is bounded (``queue_depth``) — a
        request landing on a full queue sheds with :class:`ShedError`
        (HTTP 429 + Retry-After) instead of queuing into collapse, so
        coalesced batches stay sized and every admitted request is one
        the job can actually serve.

        Whoever finds the queue shipper-less drains every waiting
        request (up to ``coalesce_max``) into ONE control-plane batch
        entry, ships it (sequence number + worker fan-out + ack round),
        hands the shipper role to the next thread, and only then
        executes the batch in its slot of the total order — so batch
        n+1's forward/ack network time overlaps batch n's device
        execution exactly like the old per-request pipeline, with the
        fixed replay overhead now amortized over the whole batch.
        Per-request results — including a request's own deterministic
        PilosaError — come back through per-item slots, so one bad
        request never poisons its batch siblings.
        """
        slot = [False, None]  # done, result (exception instance = raise)
        with self._q_cv:
            if self.queue_depth > 0 and len(self._q) >= self.queue_depth:
                self.stat_shed += 1
                raise ShedError(
                    f"lockstep arrival queue full ({self.queue_depth}); retry",
                    retry_after=0.25,
                )
            self._q.append(
                ((index, query, deadline, trace_force, tenant_hdr, _now()), slot)
            )
            while not slot[0]:
                if not self._shipping and self._q and self._inflight < 2:
                    self._shipping = True
                    self._inflight += 1
                    batch = self._q[: self.coalesce_max]
                    del self._q[: len(batch)]
                    self.stat_batches += 1
                    self.stat_requests += len(batch)
                    self._q_cv.release()
                    shipped = None
                    try:
                        shipped = self._ship_batch([it for it, _ in batch])
                    except BaseException as e:  # noqa: BLE001 — degrade
                        for _, s in batch:
                            s[1] = e
                            s[0] = True
                    finally:
                        self._q_cv.acquire()
                        self._shipping = False
                        self._q_cv.notify_all()
                    if shipped is not None:
                        self._q_cv.release()
                        try:
                            self._run_batch(
                                shipped[0], batch, shipped[1], shipped[2],
                                shipped[3], shipped[4],
                            )
                        finally:
                            self._q_cv.acquire()
                    self._inflight -= 1
                    self._q_cv.notify_all()
                    continue
                self._q_cv.wait()
        if isinstance(slot[1], BaseException):
            raise slot[1]
        return slot[1]

    # -- streaming ingest (front-end half) --------------------------------

    # Pairs per replicated SetBit body: bounds the control-plane entry
    # size and keeps each replayed body inside the native write lane's
    # sweet spot.
    _INGEST_SUBBATCH = 4096

    def _ingest_apply(self, key, rows, cols, deadline) -> int:
        """One decoded chunk -> canonical batched SetBit bodies through
        the replicated total order.  The translation keeps the wire
        JSON-clean and deterministic; each rank's executor applies the
        body through its own native batch lane."""
        index, fname = key
        idx = self.holder.index(index)
        if idx is None:
            raise ErrIndexNotFound(index)
        fr = idx.frame(fname)
        if fr is None:
            raise ErrFrameNotFound(fname)
        rl, cl = fr.row_label, idx.column_label
        rlist, clist = rows.tolist(), cols.tolist()
        for i in range(0, len(rlist), self._INGEST_SUBBATCH):
            body = "".join(
                f'SetBit({rl}={r}, frame="{fname}", {cl}={c})'
                for r, c in zip(
                    rlist[i : i + self._INGEST_SUBBATCH],
                    clist[i : i + self._INGEST_SUBBATCH],
                )
            )
            self._execute(index, body, deadline=deadline)
        return len(rlist)

    def _ingest_complete(self, key) -> None:
        index, fname = key
        self._execute(index, INGEST_RECALC_PREFIX + fname)

    def _do_ingest_recalc(self, index: str, fname: str) -> bool:
        """Executed identically on every rank (sorted iteration inside
        recalc_frame_caches): import-parity rank-cache freshness after
        a streamed ingest."""
        from pilosa_tpu import ingest as ingest_mod

        fr = self.holder.frame(index, fname)
        if fr is not None:
            ingest_mod.recalc_frame_caches(fr)
        return True

    # -- bulk build (front-end half) ---------------------------------------

    # Pairs per replicated bulk body: each entry carries base64(packed
    # uint64 pairs), so at 16 bytes/pair + 4/3 base64 overhead this is
    # ~350 KiB per control-plane entry — large enough to amortize the
    # ship/ack round, small enough to stay well under socket comfort.
    _BULK_SUBBATCH = _INGEST_SUBBATCH * 4

    def _bulk_apply(self, key, rows, cols, deadline) -> int:
        """One decoded bulk chunk -> packed-pair bodies through the
        replicated total order.  Unlike the streamed door's SetBit
        translation, the pairs ship VERBATIM (base64 of the same PI64
        packing the wire uses) and every rank runs the bulk build
        kernel over them itself — the committed overlays are a pure
        function of replicated input."""
        import base64

        from pilosa_tpu import ingest as ingest_mod

        index, fname = key
        idx = self.holder.index(index)
        if idx is None:
            raise ErrIndexNotFound(index)
        if idx.frame(fname) is None:
            raise ErrFrameNotFound(fname)
        rlist, clist = rows, cols
        for i in range(0, len(rlist), self._BULK_SUBBATCH):
            payload = base64.b64encode(
                ingest_mod.encode_packed(
                    rlist[i : i + self._BULK_SUBBATCH],
                    clist[i : i + self._BULK_SUBBATCH],
                )
            ).decode("ascii")
            self._execute(
                index,
                BULK_APPLY_PREFIX + fname + "\x00" + payload,
                deadline=deadline,
            )
        return len(rlist)

    def _bulk_complete(self, key) -> None:
        index, fname = key
        self._execute(index, BULK_RECALC_PREFIX + fname)

    def _do_bulk_apply(self, index: str, body: str) -> int:
        """Executed identically on every rank: decode the replicated
        packed pairs and run the device build + overlay commit through
        this rank's own engine (jax and numpy builds are bit-identical,
        so replicas stay digest-equal regardless of backend)."""
        import base64

        from pilosa_tpu import ingest as ingest_mod
        from pilosa_tpu.bulk import ingress

        fname, _, payload = body.partition("\x00")
        fr = self.holder.frame(index, fname)
        if fr is None:
            raise ErrFrameNotFound(fname)
        rows, cols = ingest_mod.decode_packed(base64.b64decode(payload))
        return ingress.apply_bulk(
            fr, rows, cols,
            engine=self.engine,
            executor=self.executor,
            index=index,
            batch_slices=self.bulk_batch_slices,
            stats=self.stats,
        )

    def _do_bulk_recalc(self, index: str, fname: str) -> bool:
        """Executed identically on every rank: rank-cache recalc plus
        the budgeted lazy-materialization drain.  The drain's wall-clock
        budget is rank-local, so ranks may fold different AMOUNTS of
        overlay into roaring storage here — that divergence is physical
        representation only (logical content, digests and query results
        are already identical), and any residue materializes on first
        touch."""
        from pilosa_tpu.bulk import ingress

        fr = self.holder.frame(index, fname)
        if fr is not None:
            ingress.complete_bulk(fr, self.bulk_materialize_budget_ms)
        return True

    def _ship_batch(self, items) -> tuple[int, list[bool], list, list, list]:
        """Assign the batch's slot in the total order and replicate it:
        one control-plane send per worker plus one ack round for the
        WHOLE batch (the per-request fixed cost this coalescing
        amortizes).  Returns (seq, expired flags, per-request traces).

        TRACING rides the same wire rule as deadlines: the sampling
        decision is made HERE, once, on rank 0 (forced by the client's
        X-Pilosa-Trace header or the tracer's coin flip) and ships as a
        per-request ``trace`` flag — every rank reads the flag, never
        its own RNG, so the decision is replicated.  Rank 0 builds the
        Trace objects (queue span = arrival -> ship; ship span = worker
        fan-out + receipt-ack barrier) and _run_batch closes them with
        the execute phase.

        DEADLINES ride the wire entry: expiry is decided ONCE, here on
        rank 0 at ship time, and the per-request ``expired`` flag (plus
        the remaining budget, for observability) is part of the batch
        entry — every rank drops the same expired requests before
        execution from the flag alone, never from its own clock, so the
        lockstep invariant holds without any clock sync (the same
        determinism rule as PR 2's error isolation).

        FAIL-STOP on a broken control plane: once any forward or ack
        fails, the ranks can no longer be guaranteed identical (a partial
        fan-out may have replayed a write on some ranks only), so the
        whole service degrades: new queries are refused, and in-flight
        batches behind the failed sequence error out WITHOUT executing
        locally even though live workers may replay them — after a
        degrade the replicas are presumed diverged and nothing more is
        served from any of them, so rank 0 skipping those requests is
        safe; clients retry against a restarted job (SetBit is
        idempotent).  A dead rank forces a restart exactly like the
        collective hang it would otherwise cause.
        """
        from pilosa_tpu.trace import Trace

        reqs = []
        expired: list[bool] = []
        traces: list = []
        plans: list = []
        tenants: list = []
        t_ship = _now()
        for index, query, d, tforce, thdr, t_enq in items:
            exp = bool(d is not None and d.expired())
            expired.append(exp)
            traced = self.tracer is not None and self.tracer.decide(force=tforce)
            # Tenant resolved ONCE here on rank 0 (the tenancy.resolve
            # precedence: X-Pilosa-Tenant header > [tenancy] map > index
            # name) and shipped like the expiry/trace flags — every rank
            # attributes from the wire, never from local state.
            tenant = (thdr or "").strip() or self.tenancy_index_map.get(
                index, index
            )
            tenants.append(tenant)
            entry = {"index": index, "query": query, "expired": exp,
                     "trace": traced, "tenant": tenant}
            if d is not None:
                entry["deadline_ms"] = max(0, int(d.remaining_ms()))
            # Planner decision, made ONCE here on rank 0 and shipped on
            # the wire like the expiry/trace flags: every rank applies
            # the same lane, no rank consults rank-local ledger state.
            plan = (
                self.planner.plan_for(index, query.encode())
                if self.planner is not None and not exp
                else None
            )
            plans.append(plan)
            if plan is not None:
                entry["plan"] = plan
            reqs.append(entry)
            tr = None
            if traced:
                tr = Trace(f"lockstep {index}", forced=tforce)
                # Both dimensions on the root: the cost ledger keys
                # (tenant, index, ...) without conflating them.
                tr.root.tags["tenant"] = tenant
                tr.root.tags["index"] = index
                # The queue phase already happened (arrival -> ship):
                # record it with its measured duration.
                qsp = tr.root.child("lockstep.queue")
                qsp.ms = (t_ship - t_enq) * 1e3
            traces.append(tr)
        ship_spans = [
            tr.root.child("lockstep.ship") if tr is not None else None
            for tr in traces
        ]
        with self._order_mu:
            if self._degraded:
                raise DegradedError(
                    "lockstep service degraded: control plane lost a rank; restart the job"
                )
            seq = self._next_seq
            self._next_seq += 1
            entry = {"op": "batch", "seq": seq, "reqs": reqs}
            if self.group:
                # Group identity on the wire: workers fail-stop on an
                # epoch mismatch (a stale rank 0 from a previous group
                # incarnation must never drive a restarted worker).
                entry["group"] = self.group
                entry["gepoch"] = self.group_epoch
            try:
                for w in self._workers:
                    w.settimeout(self.ack_timeout)
                    _send_msg(w, entry)
            except (OSError, socket.timeout) as e:
                raise self._degrade(e)
        try:
            self._await_acks(seq)
        except (OSError, socket.timeout) as e:
            raise self._degrade(e)
        for sp in ship_spans:
            if sp is not None:
                # Covers the worker fan-out sends plus the receipt-ack
                # barrier — the control-plane cost the batch amortizes.
                sp.finish().annotate(ranks=self.n_ranks, batch=len(items))
        return seq, expired, traces, plans, tenants

    def _exec_batch_entries(self, entries, deliver) -> None:
        """Drop expired entries (the flag decided at ship time — every
        rank sees the same flags, so every rank drops the same entries
        before execution), then run the remaining requests through the
        fused batch units.  The expired requests resolve to
        DeadlineExceeded — deterministic, so it is safe as a
        per-request result on every rank (batch siblings unaffected).
        """
        live: list = []  # (original position, (index, query), plan)
        for pos, e in enumerate(entries):
            if e.get("trace"):
                # Ship-time sampling flag off the wire: every rank sees
                # (and counts) the same flags — the determinism probe
                # the 2-rank trace test asserts on.
                self.stat_traced += 1
            ten = e.get("tenant")
            if ten:
                # Rank 0's ship-time tenant off the wire: every rank
                # tallies identical per-tenant counts (the 2-rank
                # tenancy determinism probe).
                row = self.stat_tenants.setdefault(  # analysis-ok: check-then-act: batch replay is single-threaded per rank (the control loop); stat_tenants is read only by the post-shutdown probe
                    ten, {"requests": 0, "expired": 0}
                )
                row["requests"] += 1
                if e.get("expired"):
                    row["expired"] += 1
                self.stats.count(f"tenancy.admit.{ten}")
            if e.get("expired"):
                self.stat_expired += 1
                deliver(pos, DeadlineExceeded("dropped at lockstep replay"))
            else:
                # Planner plan off the wire (rank 0's ship-time decision;
                # absent = static ladder) — applied, never re-derived.
                live.append((pos, (e["index"], e["query"]), e.get("plan")))
        if live:
            self._exec_batch_units(
                [it for _, it, _ in live],
                lambda i, result: deliver(live[i][0], result),
                plans=[p for _, _, p in live],
            )

    def _batch_units(self, items):
        """Split one replay batch into execution units.

        Maximal runs of ADJACENT same-index READ-ONLY requests fuse into
        one joined PQL execution — one parse, one fused dispatch, and
        one collective round instead of N (the per-request device
        barrier is the coalescing bench's dominant cost; the control
        plane was already amortized by the batch entry).  Writes, mixed
        requests, and unparseable requests execute alone, preserving
        their exact semantics.  The split is a pure function of the
        request strings, so every rank derives identical units — the
        lockstep invariant holds through the fusion."""
        from pilosa_tpu import pql

        units: list = []  # ("run", index, [(pos, query, n_calls)]) | ("solo", pos, index, query)
        cur: list = []
        cur_idx = None

        def flush():
            nonlocal cur, cur_idx
            if cur:
                units.append(("run", cur_idx, cur))
                cur, cur_idx = [], None

        for pos, (index, query) in enumerate(items):
            n_calls = 0
            read_only = False
            try:
                q = pql.parse_cached(query)
                n_calls = len(q.calls)
                read_only = n_calls > 0 and q.write_call_n() == 0
            # analysis-ok: exception-hygiene: unit-splitting probe; the solo execution raises the real parse error to its owner
            except Exception:  # noqa: BLE001 — parse error: solo raises it
                pass
            if read_only:
                if cur and cur_idx != index:
                    flush()
                cur_idx = index
                cur.append((pos, query, n_calls))
            else:
                flush()
                units.append(("solo", pos, index, query))
        flush()
        return units

    def _exec_batch_units(self, items, deliver, plans=None) -> None:
        """Execute one batch's units in order, reporting each request's
        result (or isolated PilosaError) through ``deliver(pos, r)``.

        ERROR ISOLATION: a PilosaError is deterministic (replicated
        holders, same total order), so every rank resolves it
        identically — it becomes that request's result only.  A fused
        read run that errors falls back to per-request execution: reads
        are side-effect-free, so the partial re-execution is safe and
        every rank repeats the same fallback.  Any OTHER exception
        propagates to the caller (rank-local failure — fail-stop).

        ``plans`` (aligned with items) carries rank 0's ship-time
        planner decisions: solo and single-read units apply theirs via
        ExecOptions.plan; MULTI-REQUEST fused runs execute without one
        (the join is its own shape — no per-request fingerprint fits),
        which is replicated because _batch_units is a pure function of
        the request strings and the plans came off the wire.
        """

        def _opt(pos):
            p = plans[pos] if plans is not None else None
            return ExecOptions(plan=p) if p is not None else None

        for unit in self._batch_units(items):
            if unit[0] == "solo":
                _, pos, index, query = unit
                if query.startswith(INGEST_RECALC_PREFIX):
                    # Reserved ingest-completion entry: recalc is a
                    # deterministic function of replicated state.
                    deliver(pos, self._do_ingest_recalc(
                        index, query[len(INGEST_RECALC_PREFIX):]
                    ))
                    continue
                if query.startswith(BULK_APPLY_PREFIX):
                    # Reserved bulk-build entry: every rank builds the
                    # same planes from the same replicated pairs.
                    try:
                        deliver(pos, self._do_bulk_apply(
                            index, query[len(BULK_APPLY_PREFIX):]
                        ))
                    except PilosaError as e:
                        deliver(pos, e)  # deterministic: isolated
                    continue
                if query.startswith(BULK_RECALC_PREFIX):
                    deliver(pos, self._do_bulk_recalc(
                        index, query[len(BULK_RECALC_PREFIX):]
                    ))
                    continue
                try:
                    deliver(pos, self.executor.execute(index, query, opt=_opt(pos)))
                except PilosaError as e:
                    deliver(pos, e)  # isolated: every rank resolved it too
                continue
            _, index, run = unit
            if len(run) > 1:
                joined = " ".join(q for _, q, _ in run)
                try:
                    res = self.executor.execute(index, joined)
                except PilosaError:
                    pass  # per-request fallback pins the error to its owner
                else:
                    off = 0
                    for pos, _q, n in run:
                        deliver(pos, res[off : off + n])
                        off += n
                    continue
            for pos, query, _n in run:
                try:
                    deliver(pos, self.executor.execute(index, query, opt=_opt(pos)))
                except PilosaError as e:
                    deliver(pos, e)

    def _run_batch(self, seq: int, batch, expired=None, traces=None,
                   plans=None, tenants=None) -> None:
        """Execute one shipped batch in its slot of the total order and
        fill every submitter's result slot; never raises (siblings would
        hang on an unfilled slot otherwise).  ``expired`` carries the
        ship-time per-request expiry flags — the SAME flags the workers
        read off the wire, so the drop is identical on every rank.
        ``traces`` carries the per-request rank-0 Trace objects for
        ship-time-sampled requests: the execute phase (this batch's
        slot wait + device execution) closes each one here and lands it
        in the tracer ring.

        Requests execute through the batch units (_exec_batch_units):
        adjacent read-only requests fuse into one executor pass,
        per-request errors stay isolated.  Any non-PilosaError failure
        means this rank may have diverged from the workers that replayed
        the batch — fail-stop: the service degrades and the batch's
        unresolved requests error out.
        """
        err = None
        with self._exec_cv:
            while self._exec_next != seq:
                if self._degraded:
                    # An earlier in-flight batch hit a lost rank: its
                    # seq will never execute here, so waiting would
                    # deadlock — every later batch reports degraded.
                    err = PilosaError(
                        "lockstep service degraded mid-flight; restart the job"
                    )
                    break
                self._exec_cv.wait(timeout=1.0)
        owned = err is None  # the wait loop exited at our slot
        try:
            if err is None and self._degraded:
                err = PilosaError(
                    "lockstep service degraded mid-batch; restart the job"
                )
            if err is None:
                def deliver(pos, result):
                    slot = batch[pos][1]
                    slot[1] = result
                    slot[0] = True

                flags = expired or [False] * len(batch)
                trs = traces or [None] * len(batch)
                pls = plans or [None] * len(batch)
                tens = tenants or [None] * len(batch)
                entries = [
                    {"index": it[0], "query": it[1], "expired": flags[i],
                     "trace": trs[i] is not None, "plan": pls[i],
                     "tenant": tens[i]}
                    for i, (it, _) in enumerate(batch)
                ]
                exec_spans = [
                    tr.root.child("lockstep.execute") if tr is not None else None
                    for tr in trs
                ]
                try:
                    self._exec_batch_entries(entries, deliver)
                except Exception as e:  # noqa: BLE001 — rank-local failure
                    self._degraded = True
                    err = e
                finally:
                    for tr, sp, (it, _) in zip(trs, exec_spans, batch):
                        if tr is None:
                            continue
                        sp.finish()
                        tr.root.finish()
                        # finish_request: ring entry + the slow-query
                        # log line when the request cleared slow-ms.
                        self.tracer.finish_request(
                            tr, name=tr.root.name, dt_ms=tr.root.ms,
                            body=it[1].encode("utf-8", errors="replace"),
                        )
            if err is not None:
                for _, slot in batch:
                    if not slot[0]:
                        slot[1] = err
                        slot[0] = True
        finally:
            if owned:
                with self._exec_cv:
                    self._exec_next = seq + 1
                    self._exec_cv.notify_all()

    class _Handler(BaseHTTPRequestHandler):
        service: "LockstepService"

        def log_message(self, *a):  # quiet
            pass

        def _group_header(self) -> None:
            from pilosa_tpu.replica import (
                APPLIED_SEQ_HEADER,
                GROUP_HEADER,
                format_group,
            )

            if self.service.group:
                self.send_header(
                    GROUP_HEADER,
                    format_group(self.service.group, self.service.group_epoch),
                )
                self.send_header(
                    APPLIED_SEQ_HEADER, str(self.service.applied_seq.value)
                )

        def do_GET(self):
            # The replica router forwards admin GETs to a group like
            # reads, so a lockstep group must answer the common
            # read-only admin surface itself (the full server's handler
            # table is not mounted here) — plus the router health probe:
            # 200 while the group can serve, 503 once degraded (a
            # restarted job answers with a bumped epoch in
            # X-Pilosa-Group).
            svc = self.service
            parsed = urlparse(self.path)
            path = parsed.path.rstrip("/") or "/"
            status = 200
            if path == "/replica/health":
                status = 503 if svc._degraded else 200
                body = json.dumps({
                    "group": svc.group,
                    "epoch": svc.group_epoch,
                    "ranks": svc.n_ranks,
                    "appliedSeq": svc.applied_seq.value,
                    "state": "DEGRADED" if svc._degraded else "UP",
                }).encode()
            elif path == "/replica/digest":
                # Content digest for the router's resync diff and
                # anti-entropy sweep.  Rank 0 computes it over its own
                # holder — the lockstep total order keeps every rank's
                # holder identical, so the digest speaks for the whole
                # group by construction (no cross-rank collective
                # needed, and no rank-local nondeterminism: the walk is
                # sorted and the checksums are pure functions of bits).
                from pilosa_tpu.replica.digest import holder_digest

                d = holder_digest(svc.holder)
                d["appliedSeq"] = svc.applied_seq.value
                body = json.dumps(d).encode()
            elif path == "/schema":
                body = json.dumps({"indexes": svc.holder.schema()}).encode()
            elif path == "/status":
                body = json.dumps({"status": {
                    "state": "DEGRADED" if svc._degraded else "UP",
                    "group": svc.group,
                    "epoch": svc.group_epoch,
                    "ranks": svc.n_ranks,
                    "appliedSeq": svc.applied_seq.value,
                    "indexes": svc.holder.schema(),
                }}).encode()
            elif path == "/slices/max":
                body = json.dumps({"maxSlices": svc.holder.max_slices()}).encode()
            elif path == "/version":
                from pilosa_tpu import __version__

                body = json.dumps({"version": __version__}).encode()
            elif path == "/debug/vars":
                body = json.dumps(svc.stats.snapshot()).encode()
            elif path == "/debug/tenants":
                # Per-tenant wire accounting (rank 0's view; every rank
                # holds the same tallies by the lockstep invariant) plus
                # the ledger billing aggregate.
                body = json.dumps({
                    "enabled": bool(svc.tenancy_index_map),
                    "tenants": {
                        t: dict(row) for t, row in svc.stat_tenants.items()
                    },
                    "ledger": (
                        svc.costs.by_tenant() if svc.costs is not None else {}
                    ),
                }).encode()
            elif path == "/metrics":
                from pilosa_tpu import metrics as metrics_mod

                body = metrics_mod.render(svc.stats).encode()
                self.send_response(status)
                self.send_header("Content-Type", metrics_mod.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self._group_header()
                self.end_headers()
                self.wfile.write(body)
                return
            elif path == "/debug/costs":
                from pilosa_tpu import metrics as metrics_mod

                params = parse_qs(parsed.query)
                limit = metrics_mod.clamp_int(
                    (params.get("limit") or [None])[0], 0
                )
                body = json.dumps(
                    svc.costs.snapshot(limit=limit)
                    if svc.costs is not None
                    else {"cap": 0, "alpha": 0.0, "entries": []}
                ).encode()
            elif path == "/debug/traces":
                from pilosa_tpu import metrics as metrics_mod

                params = parse_qs(parsed.query)
                # Clamp instead of 400 — same contract as the full
                # server's handler and the replica router.
                min_ms = metrics_mod.clamp_float(
                    (params.get("min-ms") or [None])[0], 0.0
                )
                limit = metrics_mod.clamp_int(
                    (params.get("limit") or [None])[0], 64
                )
                traces = (
                    svc.tracer.traces_json(min_ms=min_ms, limit=limit)
                    if svc.tracer is not None
                    else []
                )
                body = json.dumps({"traces": traces}).encode()
            else:
                self.send_error(404)
                return
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self._group_header()
            self.end_headers()
            self.wfile.write(body)

        def _do_ingest(self, index: str, frame: str, params: dict,
                       ingestor=None) -> None:
            """Streaming columnar ingest through the lockstep front
            end: same wire contract as the full server's route (off/
            total/crc/ccrc/probe params, packed-uint64 or Arrow chunk
            bodies); chunks replay on every rank as batched SetBit
            bodies and the completion recalc ships through the same
            total order.  ``ingestor`` selects the door sharing this
            wire contract (default the streamed-SetBit one; the /bulk
            route passes the device-build ingestor)."""
            from pilosa_tpu.ingest import IngestError
            from pilosa_tpu.replica.catchup import note_applied_from_headers

            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n) if n else b""
            headers = {k.lower(): v for k, v in self.headers.items()}
            deadline = deadline_from_headers(
                headers, self.service.default_deadline_ms
            )

            def p(name, default=None):
                v = params.get(name)
                return v[0] if v else default

            status = 200
            retry_after = None
            key = (index, frame)
            if ingestor is None:
                ingestor = self.service._ingestor
            try:
                off = int(p("off", 0))
                total = int(p("total", 0))
                crc = int(p("crc", 0))
                ccrc_s = p("ccrc")
                ccrc = int(ccrc_s) if ccrc_s is not None else None
                if p("probe") == "1":
                    out = ingestor.probe(key, total, crc)
                else:
                    arrow = "arrow" in (self.headers.get("Content-Type") or "")
                    out = ingestor.chunk(
                        key, off, total, crc, body, chunk_crc=ccrc,
                        arrow=arrow, deadline=deadline,
                    )
                body_out = json.dumps(out).encode()
            except (ValueError, TypeError):
                status = 400
                body_out = json.dumps({"error": "bad off/total/crc/ccrc"}).encode()
            except IngestError as e:
                status = e.status
                body_out = json.dumps(
                    {"error": str(e), "staged": e.staged}
                ).encode()
            except DeadlineExceeded as e:
                status = 504
                body_out = json.dumps({"error": str(e)}).encode()
            except ShedError as e:
                status = e.status
                retry_after = e.retry_after
                body_out = json.dumps({"error": str(e)}).encode()
            except DegradedError as e:
                status = 503
                retry_after = e.retry_after
                body_out = json.dumps({"error": str(e)}).encode()
            except PilosaError as e:
                status = 400
                body_out = json.dumps({"error": str(e)}).encode()
            except Exception as e:  # noqa: BLE001 — surface as 5xx
                body_out = json.dumps({"error": f"internal: {e}"}).encode()
                status = 500
            note_applied_from_headers(
                self.service.applied_seq, headers, status,
                retry_after=retry_after,
            )
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body_out)))
            if retry_after is not None:
                self.send_header("Retry-After", f"{retry_after:.3f}")
            self._group_header()
            self.end_headers()
            self.wfile.write(body_out)

        def do_POST(self):
            parsed_url = urlparse(self.path)
            parts = parsed_url.path.strip("/").split("/")
            if (
                len(parts) == 5
                and parts[0] == "index"
                and parts[2] == "frame"
                and parts[4] in ("ingest", "bulk")
            ):
                self._do_ingest(
                    parts[1], parts[3], parse_qs(parsed_url.query),
                    ingestor=(
                        self.service._bulk_ingestor
                        if parts[4] == "bulk" else None
                    ),
                )
                return
            if len(parts) != 3 or parts[0] != "index" or parts[2] != "query":
                self.send_error(404)
                return
            index = parts[1]
            n = int(self.headers.get("Content-Length", 0))
            query = self.rfile.read(n).decode("utf-8")
            headers = {k.lower(): v for k, v in self.headers.items()}
            deadline = deadline_from_headers(
                headers, self.service.default_deadline_ms
            )
            # X-Pilosa-Trace force override: the decision itself is made
            # on rank 0 at SHIP time (one place, replicated as a wire
            # flag), this only carries the client's request for it.
            trace_force = bool((headers.get("x-pilosa-trace") or "").strip())
            # X-Pilosa-Tenant override: carried to rank 0, which
            # RESOLVES the tenant once at ship time (the wire flag every
            # rank reads) — this only transports the client's claim.
            tenant_hdr = headers.get("x-pilosa-tenant")
            retry_after = None
            status = 500
            try:
                results = self.service._execute(
                    index, query, deadline=deadline, trace_force=trace_force,
                    tenant_hdr=tenant_hdr,
                )
                body = json.dumps(
                    {"results": [result_to_json(r) for r in results]}
                ).encode()
                status = 200
            except DeadlineExceeded as e:
                body = json.dumps({"error": str(e)}).encode()
                status = 504
            except ShedError as e:  # arrival queue full: back off and retry
                body = json.dumps({"error": str(e)}).encode()
                status = e.status
                retry_after = e.retry_after
            except DegradedError as e:  # control plane down: 503, not 400
                body = json.dumps({"error": str(e)}).encode()
                status = 503
                retry_after = e.retry_after
            except PilosaError as e:
                body = json.dumps({"error": str(e)}).encode()
                status = 400
            except Exception as e:  # noqa: BLE001 — a dead worker (broken
                # control pipe) or engine failure must surface as a 5xx,
                # not a silently dropped connection.
                body = json.dumps({"error": f"internal: {e}"}).encode()
                status = 500
            # Replica durability: a router-sequenced write that answered
            # deterministically (applied, or a deterministic 400) is
            # recorded as this group's applied high-water mark; sheds
            # (any answer carrying Retry-After — the shared not-applied
            # predicate), degraded 503s, and internal errors stay
            # replayable.
            from pilosa_tpu.replica.catchup import note_applied_from_headers

            note_applied_from_headers(self.service.applied_seq, headers, status,
                                      retry_after=retry_after)
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after is not None:
                self.send_header("Retry-After", f"{retry_after:.3f}")
            self._group_header()
            self.end_headers()
            self.wfile.write(body)

    # -- workers ---------------------------------------------------------

    def _epoch_ok(self, msg: dict) -> bool:
        """A control-plane entry replays only when its group identity
        matches this rank's.  Entries without the fields (legacy wire,
        or a group-less job) always pass — the guard only bites when
        BOTH sides carry an identity and they disagree."""
        if "gepoch" not in msg and "group" not in msg:
            return True
        return (
            msg.get("group", self.group) == self.group
            and int(msg.get("gepoch", self.group_epoch)) == self.group_epoch
        )

    def _worker_loop(self) -> None:
        import time

        # Rank 0 may still be binding its control listener; retry briefly
        # (the same startup race the gossip seed-join retries handle).
        deadline = time.monotonic() + self.connect_timeout
        while True:
            try:
                sock = socket.create_connection(self.control_addr, timeout=5)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)

        # Receipt acks come from a dedicated reader thread so they track
        # RECEIPT, not completion — with one loop doing recv+ack+execute,
        # rank 0's ack wait for request n+1 would block behind this
        # rank's execution of n and the pipeline depth would collapse to
        # one.  Execution itself stays strictly in arrival order.
        import queue as _queue

        jobs: "_queue.Queue[Optional[dict]]" = _queue.Queue()

        def reader():
            while True:
                msg = _recv_msg(sock)
                if msg is None or msg.get("op") == "shutdown":
                    jobs.put(None)
                    return
                try:
                    sock.sendall(b"k")  # receipt ack (rank 0 waits on these)
                except OSError:
                    jobs.put(None)
                    return
                jobs.put(msg)

        rt = threading.Thread(target=reader, daemon=True)
        rt.start()
        dead = False
        while not self._stop.is_set() and not dead:
            msg = jobs.get()
            if msg is None:
                break
            # A batch entry replays N requests in list order; a legacy
            # "query" entry is a batch of one.  Replay goes through the
            # SAME batch units as rank 0 (_exec_batch_units): adjacent
            # read-only requests fuse into one executor pass, and
            # per-request PilosaErrors are deterministic (rank 0
            # returned the same error to that request's client) and
            # resolve identically on every rank — the batch, and the
            # lockstep, continue with the next request.
            if not self._epoch_ok(msg):
                # A batch entry from a DIFFERENT group epoch: this
                # worker belongs to a restarted incarnation of the
                # group and the sender is stale (or vice versa).
                # Replaying would advance this rank's generation
                # vectors past what the group ever acknowledged —
                # fail-stop, exactly like a rank-local failure.
                print(
                    f"lockstep group epoch mismatch: entry "
                    f"{msg.get('group')}@{msg.get('gepoch')} != local "
                    f"{self.group}@{self.group_epoch}; fail-stop",
                    file=sys.stderr,
                )
                dead = True
                continue
            if msg.get("op") == "batch":
                reqs = msg["reqs"]
            else:
                reqs = [{"index": msg["index"], "query": msg["query"]}]
            try:
                # Entries marked expired at ship time are dropped HERE
                # exactly as on rank 0 — by the wire flag, never this
                # rank's clock — before any device work.
                self._exec_batch_entries(reqs, lambda pos, result: None)
            except Exception:  # noqa: BLE001
                # Rank-LOCAL failure (disk full, engine fault): this
                # replica may have diverged from its peers, so
                # fail-stop — closing the socket trips rank 0's ack
                # check on the next request and degrades the whole
                # service, rather than silently serving collectives
                # over diverged data.
                import traceback

                traceback.print_exc()
                dead = True
        sock.close()

    # -- lifecycle -------------------------------------------------------

    def serve_forever(self) -> None:
        """Run the service until :meth:`shutdown` (rank 0) or a shutdown
        message (workers).  Blocks."""
        if self.rank == 0:
            self._accept_workers()
            handler = type("Bound", (self._Handler,), {"service": self})
            self._httpd = ThreadingHTTPServer(self.http_addr or ("127.0.0.1", 0), handler)
            self.http_addr = self._httpd.server_address
            self._httpd.serve_forever(poll_interval=0.1)
        else:
            self._worker_loop()

    def shutdown(self) -> None:
        """Rank 0: stop the HTTP front end and release the workers."""
        self._stop.set()
        with self._order_mu:
            for w in self._workers:
                try:
                    _send_msg(w, {"op": "shutdown"})
                    w.close()
                except OSError:
                    pass
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if getattr(self, "_control_srv", None) is not None:
            self._control_srv.close()
