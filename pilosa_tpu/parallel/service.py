"""Lockstep multi-host query service: one HTTP front end, SPMD execution.

The reference serves distributed queries coordinator-style: the handler
node parses, fans slice batches out to peers over HTTP+protobuf, and
reduces (executor.go:1009-1244).  On a homogeneous TPU job the
TPU-native alternative is SPMD LOCKSTEP: every process holds the same
holder data, joins one ``jax.distributed`` mesh, and executes the SAME
query program; device work is sharded over the global slice axis and
XLA's collectives (psum over ICI/DCN) do the reduce that protobuf
responses did in the reference.

This module is the SERVICE shell around that execution model
(tests/test_multihost.py proves the execution model itself):

- rank 0 runs the HTTP front end (``POST /index/<name>/query``, the
  reference's wire shape, handler.go:179-243) and a control-plane TCP
  listener;
- every other rank connects to the control plane and replays, in
  arrival order, exactly the requests rank 0 serves;
- rank 0 forwards each request to all ranks BEFORE executing it
  locally, so every process enters the same jitted computations in the
  same order — the lockstep invariant the collectives require.

Requests flow through ONE total order — a sequence number assigned on
rank 0 — but execution is PIPELINED: N requests can be in flight on the
control plane (sends, receipt acks) while device execution proceeds
strictly in sequence order on every rank, so concurrent HTTP clients
overlap their network/parse time with each other's device time without
ever breaking the lockstep invariant.  Writes (SetBit etc.) replay
identically on every rank, keeping the replicated holders convergent.
Errors raised before device work (parse errors, unknown frames) raise
identically everywhere — rank 0 reports them to the client, workers log
and continue.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from pilosa_tpu.engine import MeshEngine
from pilosa_tpu.executor import Executor
from pilosa_tpu.pilosa import PilosaError
from pilosa_tpu.server.handler import result_to_json

_LEN = struct.Struct("<I")


def _send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode("utf-8")
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        if not chunk:
            return None
        head += chunk
    (n,) = _LEN.unpack(head)
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            return None
        data += chunk
    return json.loads(data.decode("utf-8"))


class LockstepService:
    """SPMD query service over a joined ``jax.distributed`` job.

    Construct AFTER ``init_multihost`` (or ``jax.distributed.initialize``)
    on every process, with identical holder contents, then call
    :meth:`serve_forever`.  Rank 0 needs ``http_addr`` and
    ``control_addr``; workers need the same ``control_addr`` to connect.
    """

    def __init__(
        self,
        holder,
        control_addr: tuple[str, int],
        http_addr: Optional[tuple[str, int]] = None,
        devices=None,
    ):
        import jax

        self.holder = holder
        self.rank = jax.process_index()
        self.n_ranks = jax.process_count()
        self.engine = MeshEngine(devices if devices is not None else jax.devices())
        self.executor = Executor(holder, engine=self.engine)
        self.control_addr = control_addr
        self.http_addr = http_addr
        self._workers: list[socket.socket] = []
        # Bound on how long rank 0 waits for a worker's receipt ack (and
        # for the send buffer to drain).  Acks come from the workers'
        # reader threads (receipt, not completion), so this only needs to
        # cover control-plane latency plus scheduling hiccups.
        self.ack_timeout = float(os.environ.get("PILOSA_TPU_LOCKSTEP_ACK_TIMEOUT", "120"))
        # PIPELINED total order: _order_mu only covers sequence assignment
        # + the worker sends (cheap), so N requests can be in flight on
        # the control plane at once; local execution is serialized in
        # sequence order by the _exec_cv gate, matching the workers'
        # socket-order replay.  _ack_mu[i]/_acked[i] track each worker's
        # ordered receipt-ack stream.
        self._order_mu = threading.Lock()
        self._next_seq = 1
        self._exec_cv = threading.Condition()
        self._exec_next = 1
        self._ack_mu: list[threading.Lock] = []
        self._acked: list[int] = []
        self._degraded = False
        self._httpd = None
        self._stop = threading.Event()

    # -- rank 0 ----------------------------------------------------------

    def _accept_workers(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(self.control_addr)
        srv.listen(self.n_ranks)
        self.control_addr = srv.getsockname()
        self._control_srv = srv
        for _ in range(self.n_ranks - 1):
            conn, _ = srv.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._workers.append(conn)
            self._ack_mu.append(threading.Lock())
            self._acked.append(0)

    def _degrade(self, e) -> "PilosaError":
        self._degraded = True
        with self._exec_cv:
            self._exec_cv.notify_all()
        return PilosaError(
            f"lockstep control plane lost a rank ({e}); "
            "service degraded — restart the job"
        )

    def _await_acks(self, seq: int) -> None:
        """Wait until every worker has acked receipt of request ``seq``.

        Each worker's control socket delivers one ack byte per request in
        order, so "acked seq n" == "n ack bytes consumed"; any thread may
        consume acks for earlier sequences on the way (the per-worker
        lock keeps consumption single-threaded).  A timeout counts as a
        lost rank — detected here instead of by hanging in the collective
        the dead worker will never enter.
        """
        for i, w in enumerate(self._workers):
            with self._ack_mu[i]:
                while self._acked[i] < seq:
                    b = w.recv(1)
                    if b != b"k":
                        raise OSError("worker closed control connection")
                    self._acked[i] += 1

    def _execute(self, index: str, query: str):
        """Forward to every worker, then run locally in sequence order.

        PIPELINED: the total order is a sequence number assigned under a
        short send-lock, so several requests can be in flight — request
        n+1's parse/forward/ack network time overlaps request n's device
        execution; local execution (and each worker's replay, by socket
        order) still happens in exactly one total order, which is the
        invariant the collectives require.

        FAIL-STOP on a broken control plane: once any forward or ack
        fails, the ranks can no longer be guaranteed identical (a partial
        fan-out may have replayed a write on some ranks only), so the
        whole service degrades: new queries are refused, and in-flight
        requests behind the failed sequence error out WITHOUT executing
        locally even though live workers may replay them — after a
        degrade the replicas are presumed diverged and nothing more is
        served from any of them, so rank 0 skipping those requests is
        safe; clients retry against a restarted job (SetBit is
        idempotent).  A dead rank forces a restart exactly like the
        collective hang it would otherwise cause.
        """
        with self._order_mu:
            if self._degraded:
                raise PilosaError(
                    "lockstep service degraded: control plane lost a rank; restart the job"
                )
            seq = self._next_seq
            self._next_seq += 1
            try:
                for w in self._workers:
                    w.settimeout(self.ack_timeout)
                    _send_msg(w, {"op": "query", "index": index, "query": query, "seq": seq})
            except (OSError, socket.timeout) as e:
                raise self._degrade(e)
        try:
            self._await_acks(seq)
        except (OSError, socket.timeout) as e:
            raise self._degrade(e)
        with self._exec_cv:
            while self._exec_next != seq:
                if self._degraded:
                    # An earlier in-flight request hit a lost rank: its
                    # seq will never execute here, so waiting would
                    # deadlock — every later request reports degraded.
                    raise PilosaError(
                        "lockstep service degraded mid-flight; restart the job"
                    )
                self._exec_cv.wait(timeout=1.0)
        try:
            return self.executor.execute(index, query)
        except PilosaError:
            raise  # deterministic; every rank raised it identically
        except Exception:
            # Workers replayed this request but rank 0 failed it:
            # the replicas may have diverged — fail-stop.
            self._degraded = True
            raise
        finally:
            with self._exec_cv:
                self._exec_next = seq + 1
                self._exec_cv.notify_all()

    class _Handler(BaseHTTPRequestHandler):
        service: "LockstepService"

        def log_message(self, *a):  # quiet
            pass

        def do_POST(self):
            parts = self.path.strip("/").split("/")
            if len(parts) != 3 or parts[0] != "index" or parts[2] != "query":
                self.send_error(404)
                return
            index = parts[1]
            n = int(self.headers.get("Content-Length", 0))
            query = self.rfile.read(n).decode("utf-8")
            try:
                results = self.service._execute(index, query)
                body = json.dumps(
                    {"results": [result_to_json(r) for r in results]}
                ).encode()
                status = 200
            except PilosaError as e:
                body = json.dumps({"error": str(e)}).encode()
                status = 400
            except Exception as e:  # noqa: BLE001 — a dead worker (broken
                # control pipe) or engine failure must surface as a 5xx,
                # not a silently dropped connection.
                body = json.dumps({"error": f"internal: {e}"}).encode()
                status = 500
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    # -- workers ---------------------------------------------------------

    def _worker_loop(self) -> None:
        import time

        # Rank 0 may still be binding its control listener; retry briefly
        # (the same startup race the gossip seed-join retries handle).
        deadline = time.monotonic() + 60
        while True:
            try:
                sock = socket.create_connection(self.control_addr, timeout=5)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)

        # Receipt acks come from a dedicated reader thread so they track
        # RECEIPT, not completion — with one loop doing recv+ack+execute,
        # rank 0's ack wait for request n+1 would block behind this
        # rank's execution of n and the pipeline depth would collapse to
        # one.  Execution itself stays strictly in arrival order.
        import queue as _queue

        jobs: "_queue.Queue[Optional[dict]]" = _queue.Queue()

        def reader():
            while True:
                msg = _recv_msg(sock)
                if msg is None or msg.get("op") == "shutdown":
                    jobs.put(None)
                    return
                try:
                    sock.sendall(b"k")  # receipt ack (rank 0 waits on these)
                except OSError:
                    jobs.put(None)
                    return
                jobs.put(msg)

        rt = threading.Thread(target=reader, daemon=True)
        rt.start()
        while not self._stop.is_set():
            msg = jobs.get()
            if msg is None:
                break
            try:
                self.executor.execute(msg["index"], msg["query"])
            except PilosaError:
                # Deterministic: rank 0 raised the same error before any
                # device work and reported it to the client; stay in
                # lockstep.
                continue
            except Exception:  # noqa: BLE001
                # Rank-LOCAL failure (disk full, engine fault): this
                # replica may have diverged from its peers, so fail-stop —
                # closing the socket trips rank 0's ack check on the next
                # request and degrades the whole service, rather than
                # silently serving collectives over diverged data.
                import traceback

                traceback.print_exc()
                break
        sock.close()

    # -- lifecycle -------------------------------------------------------

    def serve_forever(self) -> None:
        """Run the service until :meth:`shutdown` (rank 0) or a shutdown
        message (workers).  Blocks."""
        if self.rank == 0:
            self._accept_workers()
            handler = type("Bound", (self._Handler,), {"service": self})
            self._httpd = ThreadingHTTPServer(self.http_addr or ("127.0.0.1", 0), handler)
            self.http_addr = self._httpd.server_address
            self._httpd.serve_forever(poll_interval=0.1)
        else:
            self._worker_loop()

    def shutdown(self) -> None:
        """Rank 0: stop the HTTP front end and release the workers."""
        self._stop.set()
        with self._order_mu:
            for w in self._workers:
                try:
                    _send_msg(w, {"op": "shutdown"})
                    w.close()
                except OSError:
                    pass
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if getattr(self, "_control_srv", None) is not None:
            self._control_srv.close()
