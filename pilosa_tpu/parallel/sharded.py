"""Slice-axis GSPMD sharding: the TPU-native mapReduce.

The reference fans a query out with a goroutine per slice and reduces
through channels (executor.go:1115-1244).  The TPU-native equivalent keeps
the whole slice batch as ONE array ``uint32[n_slices, W]`` sharded along a
``slice`` mesh axis:

- elementwise set ops stay local to each shard (no communication),
- ``Count`` reduces with ``lax.psum`` over the slice axis (ICI all-reduce
  with integer SUM — the analog of the coordinator summing per-node
  counts),
- bitmap materialization all-gathers shards (``lax.all_gather``, the
  analog of streaming per-node segment lists back),
- TopN candidate merge all-gathers per-shard (id, count) pairs.

Two styles are provided: explicit ``shard_map`` kernels (collectives
spelled out — used by the dryrun and the benchmarks) and NamedSharding
placement helpers that let GSPMD infer the same collectives for ad-hoc
jnp expressions.
"""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np


def _shard_map(jax):
    """Compat shim: ``jax.shard_map`` (with ``check_vma``) is the
    current API; older releases only have
    ``jax.experimental.shard_map.shard_map`` (with ``check_rep``).
    Returns a callable with the CURRENT keyword surface either way, so
    every kernel below writes modern code once."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    from jax.experimental.shard_map import shard_map as legacy

    def adapted(f, *, mesh, in_specs, out_specs, check_vma=True):
        return legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )

    return adapted


class SliceMesh:
    """A 1-D device mesh over the ``slice`` axis.

    The in-pod replacement for the reference's hash-ring placement
    (cluster.go:198-240): slice i of a stacked batch lives on device
    ``i * n_devices // n_slices`` deterministically via GSPMD row
    sharding; no per-slice routing table is needed.
    """

    AXIS = "slice"

    def __init__(self, devices: Sequence | None = None):
        import jax
        from jax.sharding import Mesh

        self.jax = jax
        devices = list(devices if devices is not None else jax.devices())
        self.mesh = Mesh(np.array(devices), (self.AXIS,))
        self.n_devices = len(devices)

    def sharding(self, *rest_dims_replicated: int):
        """NamedSharding: leading dim split over slice axis, rest replicated."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P(self.AXIS, *([None] * len(rest_dims_replicated))))

    def shard_stack(self, x: np.ndarray):
        """Place [n_slices, ...] with the leading axis sharded over devices."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(self.AXIS, *([None] * (x.ndim - 1)))
        return self.jax.device_put(x, NamedSharding(self.mesh, spec))

    def replicate(self, x: np.ndarray):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return self.jax.device_put(x, NamedSharding(self.mesh, P(*([None] * x.ndim))))


def _require_divisible(n_slices: int, n_devices: int) -> None:
    if n_slices % n_devices:
        raise ValueError(
            f"slice count {n_slices} must be a multiple of mesh size {n_devices}; "
            "pad the stack with zero slices"
        )


def sharded_count_and(mesh: SliceMesh, a, b):
    """Global |a & b| over a slice-sharded stack: fused local popcount +
    psum over ICI (the Count(Intersect(..)) hot path, distributed)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    @functools.partial(
        _shard_map(jax),
        mesh=mesh.mesh,
        in_specs=(P(mesh.AXIS, None), P(mesh.AXIS, None)),
        out_specs=P(),
        check_vma=False,
    )
    def kernel(a_shard, b_shard):
        local = jnp.sum(
            lax.population_count(jnp.bitwise_and(a_shard, b_shard)).astype(jnp.int32)
        )
        return lax.psum(local, mesh.AXIS)

    return jax.jit(kernel)(a, b)


def sharded_union_reduce(mesh: SliceMesh, stacks):
    """OR together several slice-sharded stacks; result stays sharded.

    Union over operands needs NO communication — each shard ORs its own
    rows.  (The cross-*slice* direction is never reduced for bitmaps; a
    bitmap result is naturally slice-partitioned, as in the reference's
    per-slice segment lists.)
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(*xs):
        out = xs[0]
        for x in xs[1:]:
            out = jnp.bitwise_or(out, x)
        return out

    return kernel(*stacks)


def sharded_count_call(mesh: SliceMesh, op: str, a, b):
    """Fused count of an arbitrary pairwise set op over sharded stacks."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    def apply_op(x, y):
        if op == "and":
            return jnp.bitwise_and(x, y)
        if op == "or":
            return jnp.bitwise_or(x, y)
        if op == "xor":
            return jnp.bitwise_xor(x, y)
        if op == "andnot":
            return jnp.bitwise_and(x, jnp.bitwise_not(y))
        raise ValueError(op)

    @functools.partial(
        _shard_map(jax),
        mesh=mesh.mesh,
        in_specs=(P(mesh.AXIS, None), P(mesh.AXIS, None)),
        out_specs=P(),
        check_vma=False,
    )
    def kernel(a_shard, b_shard):
        local = jnp.sum(lax.population_count(apply_op(a_shard, b_shard)).astype(jnp.int32))
        return lax.psum(local, mesh.AXIS)

    return jax.jit(kernel)(a, b)


@functools.lru_cache(maxsize=None)
def _sharded_pair_kernel(
    mesh_obj, axis: str, op: str, resident: bool, interpret: bool, rm_ndim: int = 3
):
    """Jitted shard_map'd Pallas pair-count kernel, cached per (mesh, op,
    strategy) — a fresh closure per call would retrace + recompile every
    query (jax.Mesh is hashable, so it keys the cache directly).
    ``rm_ndim`` supports both the 3D logical and 4D tiled matrix forms."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from pilosa_tpu.ops.pallas_kernels import (
        fused_gather_count2,
        fused_resident_count2,
    )

    @functools.partial(
        _shard_map(jax),
        mesh=mesh_obj,
        in_specs=(P(axis, *([None] * (rm_ndim - 1))), P(None, None)),
        out_specs=P(),
        check_vma=False,
    )
    def kernel(rm_shard, prs):
        if resident:
            local = fused_resident_count2(op, rm_shard, prs, interpret=interpret)
        else:
            local = fused_gather_count2(op, rm_shard, prs, interpret=interpret)
        return lax.psum(local, axis)

    return jax.jit(kernel)


@functools.lru_cache(maxsize=None)
def _sharded_multi_kernel(mesh_obj, axis: str, op: str, interpret: bool, rm_ndim: int = 3):
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from pilosa_tpu.ops.pallas_kernels import fused_gather_count_multi

    @functools.partial(
        _shard_map(jax),
        mesh=mesh_obj,
        in_specs=(P(axis, *([None] * (rm_ndim - 1))), P(None, None)),
        out_specs=P(),
        check_vma=False,
    )
    def kernel(rm_shard, ids):
        local = fused_gather_count_multi(op, rm_shard, ids, interpret=interpret)
        return lax.psum(local, axis)

    return jax.jit(kernel)


# The Pallas kernels scalar-prefetch the pair ids into SMEM; bound the
# per-dispatch id footprint exactly like single-chip dispatch does
# (observed hard failure at B=4096 on v5e, see ops/dispatch.py).
_SHARDED_BATCH_MAX = 1024


def sharded_gather_count(
    mesh: SliceMesh, op: str, row_matrix, pairs, interpret: bool = False
):
    """Batched pair counts with the HAND-TUNED Pallas kernels under GSPMD.

    ``shard_map`` gives each device its local ``[S/n, R, W]`` block of the
    slice-sharded row matrix; inside the per-shard body the same Pallas
    kernels as single-chip dispatch run (resident or gather strategy by
    the SHARD's shape, shared predicate), and ``lax.psum`` merges the
    per-shard counts over ICI — multi-chip execution keeps the kernel
    tier instead of demoting to the jnp fallback.  ``interpret=True``
    runs the kernels in Pallas interpret mode (CPU meshes: tests and the
    driver dryrun).

    Requires the slice axis divisible by the mesh; callers fall back to
    the GSPMD-partitioned jnp form otherwise.
    """
    import jax.numpy as jnp

    from pilosa_tpu.ops.pallas_kernels import resident_strategy, rm_words

    n_slices, n_rows = row_matrix.shape[:2]
    w = rm_words(row_matrix)
    _require_divisible(n_slices, mesh.n_devices)
    b = pairs.shape[0]
    if b > _SHARDED_BATCH_MAX:
        return jnp.concatenate(
            [
                sharded_gather_count(
                    mesh, op, row_matrix, pairs[i : i + _SHARDED_BATCH_MAX], interpret
                )
                for i in range(0, b, _SHARDED_BATCH_MAX)
            ]
        )
    kernel = _sharded_pair_kernel(
        mesh.mesh, mesh.AXIS, op, resident_strategy(n_rows, w, b), interpret,
        row_matrix.ndim,
    )
    return kernel(row_matrix, pairs)


def sharded_gather_count_multi(
    mesh: SliceMesh, op: str, row_matrix, idx, interpret: bool = False
):
    """Multi-operand fold counts (N-ary Intersect/Union/Difference, Range
    covers) through the Pallas multi-gather kernel per shard + psum.
    Chunks the batch so prefetched ids stay inside the SMEM budget."""
    import jax.numpy as jnp

    n_slices = row_matrix.shape[0]
    _require_divisible(n_slices, mesh.n_devices)
    b, k = idx.shape
    chunk = max(1, (2 * _SHARDED_BATCH_MAX) // max(1, k))
    if b > chunk:
        return jnp.concatenate(
            [
                sharded_gather_count_multi(
                    mesh, op, row_matrix, idx[i : i + chunk], interpret
                )
                for i in range(0, b, chunk)
            ]
        )
    kernel = _sharded_multi_kernel(mesh.mesh, mesh.AXIS, op, interpret, row_matrix.ndim)
    return kernel(row_matrix, idx)


@functools.lru_cache(maxsize=None)
def _sharded_tree_kernel(mesh_obj, axis: str, interpret: bool, rm_ndim: int = 3):
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from pilosa_tpu.ops.pallas_kernels import fused_gather_count_tree

    @functools.partial(
        _shard_map(jax),
        mesh=mesh_obj,
        in_specs=(P(axis, *([None] * (rm_ndim - 1))), P(None, None), P(None, None)),
        out_specs=P(),
        check_vma=False,
    )
    def kernel(rm_shard, leaves, opc):
        local = fused_gather_count_tree(rm_shard, leaves, opc, interpret=interpret)
        return lax.psum(local, axis)

    return jax.jit(kernel)


def sharded_gather_count_tree(
    mesh: SliceMesh, row_matrix, leaves, opc, interpret: bool = False
):
    """Arbitrary nested tree counts through the Pallas tree kernel per
    shard + psum (the multi-chip form of dispatch.gather_count_tree —
    executor.go:261-276 fused over the mesh).  Chunks the batch so the
    prefetched leaf ids + opcodes stay inside the SMEM budget."""
    import jax.numpy as jnp

    n_slices = row_matrix.shape[0]
    _require_divisible(n_slices, mesh.n_devices)
    b, k = leaves.shape
    chunk = max(1, (2 * _SHARDED_BATCH_MAX) // max(1, 2 * k - 1))
    if b > chunk:
        return jnp.concatenate(
            [
                sharded_gather_count_tree(
                    mesh, row_matrix, leaves[i : i + chunk], opc[i : i + chunk],
                    interpret,
                )
                for i in range(0, b, chunk)
            ]
        )
    kernel = _sharded_tree_kernel(mesh.mesh, mesh.AXIS, interpret, row_matrix.ndim)
    return kernel(row_matrix, leaves, opc)


@functools.lru_cache(maxsize=None)
def _sharded_scorer_kernel(mesh_obj, axis: str, rm_ndim: int, src_ndim: int):
    """Jitted shard_map'd scorer kernel, cached per (mesh, layouts) — a
    fresh closure per call would retrace + recompile every candidate
    chunk (same policy as _sharded_pair_kernel above)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    @functools.partial(
        _shard_map(jax),
        mesh=mesh_obj,
        in_specs=(
            P(axis, *([None] * (rm_ndim - 1))),
            P(None),
            P(axis, *([None] * (src_ndim - 1))),
        ),
        out_specs=P(axis, None),
        check_vma=False,
    )
    def kernel(rm, idv, s):
        g = jnp.take(rm, idv, axis=1)  # [s_local, k, ...words]
        inter = g & s[:, None]
        axes = tuple(range(2, g.ndim))
        return jnp.sum(lax.population_count(inter).astype(jnp.int32), axis=axes)

    return jax.jit(kernel)


def sharded_scorer_counts(mesh: SliceMesh, rows, ids, src, chunk: int = 64):
    """Per-(slice, candidate) intersection counts for TopN scoring on a
    slice-sharded row matrix — the multi-host-safe form of the engine row
    scorer (eagerly indexing ``matrix[si]`` only works when every shard
    is process-addressable).

    rows: uint32[S, cap, ...] sharded on slice (3D logical or 4D tiled);
    ids: int32[K] replicated slot ids; src: [S, ...] sharded, same word
    layout as rows.  Returns int32[S, K] sharded on slice — each rank
    fetches it with an allgather-aware fetch and feeds its per-fragment
    heap logic.  The gather transient is bounded by ``chunk`` candidates
    per dispatch.
    """
    import jax.numpy as jnp

    _require_divisible(rows.shape[0], mesh.n_devices)
    kernel = _sharded_scorer_kernel(mesh.mesh, mesh.AXIS, rows.ndim, src.ndim)
    k = ids.shape[0]
    if k > chunk:
        return jnp.concatenate(
            [kernel(rows, ids[i : i + chunk], src) for i in range(0, k, chunk)],
            axis=1,
        )
    return kernel(rows, ids, src)


def sharded_topn_counts(mesh: SliceMesh, rows, src):
    """Per-row global intersection counts for TopN over a sharded slice axis.

    rows: uint32[n_slices, n_rows, W] sharded on slice; src: uint32[n_slices, W]
    sharded on slice.  Returns int32[n_rows] — each row's count summed over
    every slice (psum over ICI), ready for host-side heap/threshold logic.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    @functools.partial(
        _shard_map(jax),
        mesh=mesh.mesh,
        in_specs=(P(mesh.AXIS, None, None), P(mesh.AXIS, None)),
        out_specs=P(),
        check_vma=False,
    )
    def kernel(rows_shard, src_shard):
        inter = jnp.bitwise_and(rows_shard, src_shard[:, None, :])
        local = jnp.sum(lax.population_count(inter).astype(jnp.int32), axis=(0, 2))
        return lax.psum(local, mesh.AXIS)

    return jax.jit(kernel)(rows, src)


# ---------------------------------------------------------------------------
# Replica groups: 2-D (slice x replica) mesh
# ---------------------------------------------------------------------------

class ReplicaMesh(SliceMesh):
    """A 2-D device mesh (slice x replica): the ReplicaN analog.

    The reference assigns each partition to ``ReplicaN`` consecutive
    ring nodes (cluster.go:220-240) so every slice has replica_n owners.
    The TPU-native form: devices arranged as a 2-D mesh whose ``slice``
    axis shards the bitmap stacks and whose ``replica`` axis holds full
    copies — placement is the sharding annotation, no routing table.

    What the replicas buy, TPU-first:
    - fault tolerance: either replica group holds the full index; a
      failed host's job restarts against the surviving group (the
      in-pod analog of query-time replica failover,
      executor.go:1147-1159);
    - READ parallelism: a query batch splits across the replica axis —
      each replica group answers its sub-batch against its full copy,
      psum runs over ``slice`` WITHIN each group (XLA emits the
      all-reduce with replica-group participant lists), and the batch
      reassembles over the ``replica`` axis.  replica_n groups serve
      replica_n x the read throughput, the same reason the reference
      fans reads over any owner node.

    Multi-pod: pass ``hybrid=True`` to lay the replica axis across DCN
    (``mesh_utils.create_hybrid_device_mesh``) so the slice-axis psum
    rides ICI inside each pod and only rare cross-replica traffic
    crosses DCN.  Single-pod/virtual meshes use a plain 2-D reshape.
    """

    REPLICA_AXIS = "replica"

    def __init__(self, n_replicas: int = 2, devices: Sequence | None = None,
                 hybrid: bool = False):
        import jax
        from jax.sharding import Mesh

        self.jax = jax
        devices = list(devices if devices is not None else jax.devices())
        if len(devices) % n_replicas:
            raise ValueError(
                f"{len(devices)} devices not divisible into {n_replicas} replica groups"
            )
        n_slice = len(devices) // n_replicas
        if hybrid:
            from jax.experimental import mesh_utils

            # DCN granules (pods) are the OUTER blocks of the returned
            # array: flat = [pod0 devices..., pod1 devices...].  Each pod
            # is one replica group, so pods index the REPLICA axis —
            # reshape (n_replicas, n_slice) then transpose, keeping the
            # slice-axis psum on ICI within a pod and only cross-replica
            # traffic on DCN.
            try:
                dev_array = np.asarray(
                    mesh_utils.create_hybrid_device_mesh(
                        (n_slice,), (n_replicas,), devices=devices,
                    )
                ).reshape(n_replicas, n_slice).T
            # analysis-ok: exception-hygiene: topology probe; the guarded fallback below is the point (mesh.hybrid records which was built)
            except Exception:  # noqa: BLE001 — no DCN topology on this host
                # Hosts without a DCN topology (single-process CPU runs,
                # one-host TPU boxes: every device is one granule, and
                # create_hybrid_device_mesh needs >= n_replicas of them)
                # fall back to a plain create_device_mesh reshape, so a
                # hybrid request never needs real multi-pod hardware.
                hybrid = False
                dev_array = self._flat_2d(n_replicas, n_slice, devices)
        else:
            dev_array = self._flat_2d(n_replicas, n_slice, devices)
        self.hybrid = hybrid  # the layout actually BUILT, post-fallback
        self.mesh = Mesh(dev_array, (self.AXIS, self.REPLICA_AXIS))
        # SliceMesh API compat: helpers divide the slice axis by this.
        self.n_devices = n_slice
        self.n_replicas = n_replicas

    @staticmethod
    def _flat_2d(n_replicas: int, n_slice: int, devices) -> np.ndarray:
        """(slice, replica) layout without DCN awareness: consecutive
        (ICI-adjacent) devices run along the slice axis within one
        replica group.  ``create_device_mesh`` keeps physical adjacency
        on real TPU topologies; virtual/CPU device lists (no coords)
        fall through to a plain reshape with the same orientation."""
        try:
            from jax.experimental import mesh_utils

            return np.asarray(
                mesh_utils.create_device_mesh(
                    (n_replicas, n_slice), devices=devices
                )
            ).T
        # analysis-ok: exception-hygiene: topology probe; plain reshape is the documented fallback
        except Exception:  # noqa: BLE001 — virtual devices without topology
            return np.array(devices).reshape(n_replicas, n_slice).T


def replica_gather_count(mesh: ReplicaMesh, op: str, row_matrix, pairs,
                         interpret: bool = False):
    """Batched pair counts on a (slice x replica) mesh with the batch
    SPLIT over the replica axis: each replica group runs the Pallas
    kernel on its sub-batch against its full slice-sharded copy, psum
    reduces over ``slice`` within the group (replica-group all-reduce),
    and the result reassembles along ``replica``.

    pairs: int32[B, 2] with B divisible by n_replicas.  Returns int32[B].
    """
    from pilosa_tpu.ops.pallas_kernels import resident_strategy, rm_words

    n_slices, n_rows = row_matrix.shape[:2]
    _require_divisible(n_slices, mesh.n_devices)
    b = pairs.shape[0]
    if b % mesh.n_replicas:
        raise ValueError(f"batch {b} not divisible by {mesh.n_replicas} replicas")
    kernel = _replica_pair_kernel(
        mesh.mesh, mesh.AXIS, mesh.REPLICA_AXIS, op,
        resident_strategy(n_rows, rm_words(row_matrix), b // mesh.n_replicas),
        interpret, row_matrix.ndim,
    )
    return kernel(row_matrix, pairs)


@functools.lru_cache(maxsize=None)
def _replica_pair_kernel(mesh_obj, slice_axis: str, replica_axis: str, op: str,
                         resident: bool, interpret: bool, rm_ndim: int):
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from pilosa_tpu.ops.pallas_kernels import (
        fused_gather_count2,
        fused_resident_count2,
    )

    @functools.partial(
        _shard_map(jax),
        mesh=mesh_obj,
        # Matrix: sharded over slice, REPLICATED over replica (each
        # group holds a full copy).  Pairs: split over replica.
        in_specs=(P(slice_axis, *([None] * (rm_ndim - 1))), P(replica_axis, None)),
        out_specs=P(replica_axis),
        check_vma=False,
    )
    def kernel(rm_shard, prs_shard):
        if resident:
            local = fused_resident_count2(op, rm_shard, prs_shard, interpret=interpret)
        else:
            local = fused_gather_count2(op, rm_shard, prs_shard, interpret=interpret)
        # Replica-group reduce: psum over the slice axis only — XLA emits
        # the all-reduce with one participant group per replica.
        return lax.psum(local, slice_axis)

    return jax.jit(kernel)
