"""Group-commit ingest queue: concurrent singleton writes -> one batch.

The reference ingests singleton SetBits at a few hundred ns each because
its whole write path is compiled Go (fragment.go:371-459).  Here the
per-op interpreter cost is the bottleneck, so the server routes singleton
SetBit requests through a micro-batching queue: whoever finds the queue
leaderless commits ONE drained batch (a vectorized fragment pass + one
WAL append per touched view/slice), then hands leadership off — under
sustained load leadership rotates FIFO through the waiting threads, so no
request is starved behind other clients' batches.  An idle queue adds no
artificial latency (the first writer leads immediately; no timer).

Read-your-writes: a client's next request can only be sent after its ack,
and the ack happens after the batch (including its op) committed, so its
subsequent reads observe the write.  Per-item errors: apply_batch may
return an exception INSTANCE as an item's result — it is raised on that
submitter only; an exception RAISED by apply_batch poisons the whole
batch (transport-level failures; SetBit is idempotent, retries converge).
"""

from __future__ import annotations

import threading

from pilosa_tpu.analysis import lockcheck
from typing import Callable, Sequence


class WriteQueue:
    """Rotating-leader group commit (no dedicated thread, no idle timer)."""

    def __init__(self, apply_batch: Callable[[Sequence], list], max_batch: int = 4096):
        self._apply = apply_batch
        self.max_batch = max_batch
        self._mu = lockcheck.named_lock("ingest._mu")
        self._cv = lockcheck.named_condition("ingest._mu", self._mu)
        self._items: list = []  # [(item, slot)]
        self._committing = False
        # Telemetry: batches committed / items seen (bench + tests).
        self.stat_batches = 0
        self.stat_items = 0

    def submit(self, item):
        """Enqueue one item; blocks until its batch commits.  Returns the
        per-item result from apply_batch (raising it if it is an
        exception), or raises the whole batch's error."""
        slot = [False, None, None]  # done, result, exception
        with self._cv:
            self._items.append((item, slot))
            while not slot[0]:
                if not self._committing and self._items:
                    # Leaderless with work pending: this thread commits
                    # exactly ONE batch, then re-checks its own slot —
                    # leadership rotates instead of camping on one thread.
                    self._committing = True
                    batch = self._items[: self.max_batch]
                    del self._items[: len(batch)]
                    self.stat_batches += 1
                    self.stat_items += len(batch)
                    self._mu.release()
                    try:
                        results = self._apply([it for it, _ in batch])
                        for (_, s), r in zip(batch, results):
                            s[1] = r
                            s[0] = True
                    except BaseException as e:  # noqa: BLE001 — poison batch
                        for _, s in batch:
                            s[2] = e
                            s[0] = True
                    finally:
                        self._mu.acquire()
                        self._committing = False
                        self._cv.notify_all()
                    continue
                self._cv.wait()
        if slot[2] is not None:
            raise slot[2]
        if isinstance(slot[1], BaseException):
            raise slot[1]
        return slot[1]
