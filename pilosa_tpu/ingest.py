"""Ingest front doors: group-commit write queue + columnar streaming.

Two ingest mechanisms live here:

1. :class:`WriteQueue` — the group-commit micro-batching queue for
   concurrent singleton SetBit requests (below).

2. :class:`StreamIngestor` — the columnar streaming bulk-ingest door
   (``POST /index/<i>/frame/<f>/ingest``): zero-tuple (row, col)
   column chunks — Arrow IPC record batches when ``pyarrow`` is
   importable, the length-prefixed packed-uint64 framing otherwise —
   decoded straight into numpy arrays and applied through the batched
   ``Frame.set_bits`` path.  Per-chunk CRC, resumable offsets
   (mirroring the import-roaring staging), deadline checks between
   chunks, and an import-parity rank-cache recalculation at transfer
   completion.  Transport-agnostic: the HTTP handler and the lockstep
   front end both drive it; the replica router classifies the route as
   a write, so chunks are sequenced, WAL-logged, and replayed like any
   other write (re-applying a chunk is idempotent — SetBit converges).

Group-commit ingest queue: concurrent singleton writes -> one batch.

The reference ingests singleton SetBits at a few hundred ns each because
its whole write path is compiled Go (fragment.go:371-459).  Here the
per-op interpreter cost is the bottleneck, so the server routes singleton
SetBit requests through a micro-batching queue: whoever finds the queue
leaderless commits ONE drained batch (a vectorized fragment pass + one
WAL append per touched view/slice), then hands leadership off — under
sustained load leadership rotates FIFO through the waiting threads, so no
request is starved behind other clients' batches.  An idle queue adds no
artificial latency (the first writer leads immediately; no timer).

Read-your-writes: a client's next request can only be sent after its ack,
and the ack happens after the batch (including its op) committed, so its
subsequent reads observe the write.  Per-item errors: apply_batch may
return an exception INSTANCE as an item's result — it is raised on that
submitter only; an exception RAISED by apply_batch poisons the whole
batch (transport-level failures; SetBit is idempotent, retries converge).
"""

from __future__ import annotations

import struct
import threading
import zlib

from pilosa_tpu.analysis import lockcheck
from typing import Callable, Optional, Sequence

# -- columnar chunk wire formats --------------------------------------------

# Packed-uint64 framing: [b"PI64"][u32 n LE][rows u64*n LE][cols u64*n LE].
PACKED_MAGIC = b"PI64"

# Arrow IPC stream content type (record batches with uint64 columns
# "row" and "col"); served only when pyarrow is importable.
ARROW_CONTENT_TYPE = "application/vnd.apache.arrow.stream"


def arrow_available() -> bool:
    try:
        import pyarrow  # noqa: F401

        return True
    except ImportError:
        return False


class IngestError(Exception):
    """Chunk rejected; ``status`` maps to the HTTP answer and
    ``staged`` tells a resuming sender where the transfer stands."""

    def __init__(self, status: int, message: str, staged: int = 0):
        super().__init__(message)
        self.status = status
        self.staged = staged


def encode_packed(rows, cols) -> bytes:
    """Encode one packed-uint64 chunk (client/bench/test helper)."""
    import numpy as np

    rows = np.ascontiguousarray(rows, dtype="<u8")
    cols = np.ascontiguousarray(cols, dtype="<u8")
    if len(rows) != len(cols):
        raise ValueError("row/col length mismatch")
    return (
        PACKED_MAGIC + struct.pack("<I", len(rows))
        + rows.tobytes() + cols.tobytes()
    )


def decode_packed(body: bytes):
    """Decode a packed-uint64 chunk -> (rows u64[n], cols u64[n]);
    zero-copy views over the request body."""
    import numpy as np

    if len(body) < 8 or body[:4] != PACKED_MAGIC:
        raise IngestError(400, "bad chunk: missing PI64 header")
    (n,) = struct.unpack_from("<I", body, 4)
    if len(body) != 8 + 16 * n:
        raise IngestError(
            400, f"bad chunk: declared {n} pairs, got {len(body) - 8} payload bytes"
        )
    rows = np.frombuffer(body, dtype="<u8", count=n, offset=8)
    cols = np.frombuffer(body, dtype="<u8", count=n, offset=8 + 8 * n)
    return rows, cols


def _arrow_u64_column(pa, table, name):
    """One named column of an Arrow table as a uint64 numpy array.

    Tolerant of real producer variety: chunked columns concatenate,
    dictionary-encoded columns decode to their value type, and any
    integer type casts (safely) to uint64.  A missing column or a
    non-integer type raises a POINTED 400 naming the problem — schema
    mistakes at 100M rows must not read as 'bad arrow chunk: KeyError'.
    """
    import numpy as np

    if name not in table.column_names:
        raise IngestError(
            400,
            f"bad arrow chunk: missing required column {name!r} "
            f"(present: {table.column_names})",
        )
    col = table.column(name)
    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    if pa.types.is_dictionary(col.type):
        col = col.dictionary_decode()
    if not pa.types.is_integer(col.type):
        raise IngestError(
            400,
            f"bad arrow chunk: column {name!r} has type {col.type}, "
            "expected an integer type castable to uint64",
        )
    try:
        col = col.cast(pa.uint64())
    except pa.ArrowInvalid as e:
        raise IngestError(
            400, f"bad arrow chunk: column {name!r} not castable to uint64: {e}"
        )
    return np.ascontiguousarray(
        col.to_numpy(zero_copy_only=False), dtype=np.uint64
    )


def decode_arrow(body: bytes):
    """Decode an Arrow IPC stream chunk -> (rows, cols) uint64 arrays.

    Requires uint64-castable ``row`` and ``col`` columns; extra columns
    are ignored (producers often ship their full table), dictionary
    encoding and multi-chunk columns are accepted.  415 without pyarrow,
    pointed 400s for schema mistakes."""
    try:
        import pyarrow as pa
    except ImportError:
        raise IngestError(
            415, "arrow ingest unavailable: pyarrow not importable on this server"
        )
    try:
        table = pa.ipc.open_stream(body).read_all()
    except (pa.ArrowInvalid, ValueError) as e:
        raise IngestError(400, f"bad arrow chunk: {e}")
    return (
        _arrow_u64_column(pa, table, "row"),
        _arrow_u64_column(pa, table, "col"),
    )


def apply_columnar(frame, rows, cols, executor=None, index: str = "",
                   deadline=None):
    """Apply one decoded columnar chunk through the batched write path:
    one vectorized ``set_bits`` pass per touched (view, slice) — no
    Python tuples, no per-op parse.  Mirrors the import path's view
    fan-out (standard + inverse when enabled; the wire carries no
    timestamps, so no time views).  Returns the changed count."""
    import numpy as np

    from pilosa_tpu.core.view import VIEW_INVERSE, VIEW_STANDARD

    rows = np.asarray(rows, dtype=np.uint64)
    cols = np.asarray(cols, dtype=np.uint64)
    ch = frame.set_bits(VIEW_STANDARD, rows, cols)
    if deadline is not None:
        deadline.check("ingest apply")
    if frame.inverse_enabled:
        frame.set_bits(VIEW_INVERSE, cols, rows)
    if executor is not None and ch.any():
        executor.note_external_write(
            index, frame.name, np.unique(rows[ch]).tolist()
        )
    return int(ch.sum())


def recalc_frame_caches(frame) -> None:
    """Import-parity rule: bulk ingest recalculates rank-cache rankings
    IMMEDIATELY at transfer completion (a TopN right after a streamed
    ingest must be fresh, not ranking-debounce stale).  Iteration is
    sorted — this runs on every lockstep rank."""
    for vname in sorted(frame.views):
        view = frame.views[vname]
        for s in sorted(view.fragments):
            view.fragments[s].recalculate_cache()


@lockcheck.guarded_class
class StreamIngestor:
    """Staged, resumable columnar streaming ingest (transport-agnostic).

    One in-progress transfer per (index, frame) key, identified by the
    whole payload's ``(total, crc)`` — a different pair restarts the
    transfer.  Chunks must arrive at the staged offset; an idempotent
    re-send of an already-applied chunk acks with the staged offset
    (SetBit converges), a gap answers 409 + ``staged`` so the sender
    resumes.  Unlike the import-roaring stager, chunks are APPLIED as
    they arrive (constant memory — the transfer state is offsets and a
    running CRC, never the payload), so "resume" means re-telling the
    sender where the applied frontier is.  At completion the running
    CRC is checked against the declared one and the ``complete`` hook
    runs (rank-cache recalculation).
    """

    # Lockset race detector declaration: the transfer table (offsets,
    # running CRCs, busy flags) is written by concurrent chunk uploads;
    # the in-place dict mutations are covered by the static
    # guarded-fields rule, a rebind by the runtime lockset check.
    _guarded_by_ = {"_transfers": "ingest.stream._mu"}

    def __init__(self, apply: Callable, complete: Optional[Callable] = None,
                 stats=None, max_transfers: int = 256,
                 max_chunk_bytes: int = 4 << 20):
        from pilosa_tpu.stats import NOP_STATS

        self._apply = apply  # (key, rows, cols, deadline) -> changed count
        self._complete = complete  # (key) -> None
        self.stats = stats if stats is not None else NOP_STATS
        self.max_transfers = max_transfers
        self.max_chunk_bytes = max_chunk_bytes
        self._mu = lockcheck.named_lock("ingest.stream._mu")
        self._transfers: dict = {}  # key -> state dict

    def probe(self, key, total: int, crc: int) -> dict:
        """Where does (key, total, crc)'s transfer stand?  (The resume
        question a restarted sender asks before streaming.)"""
        with self._mu:
            st = self._transfers.get(key)
            if st is None or st["total"] != total or st["crc"] != crc:
                return {"staged": 0, "done": False}
            return {"staged": st["off"], "done": False}

    def chunk(self, key, off: int, total: int, crc: int, body: bytes,
              chunk_crc: Optional[int] = None, arrow: bool = False,
              deadline=None) -> dict:
        """Stage-and-apply one chunk; returns ``{"staged", "done",
        "ops"}`` or raises :class:`IngestError` (offset gap, CRC
        mismatch, malformed chunk, oversized chunk)."""
        if total < 0 or off < 0:
            raise IngestError(400, "bad off/total")
        if len(body) > self.max_chunk_bytes:
            raise IngestError(
                413,
                f"chunk of {len(body)} bytes exceeds the "
                f"{self.max_chunk_bytes}-byte door; split the stream",
            )
        if total == 0:
            return {"staged": 0, "done": True, "ops": 0}
        with self._mu:
            st = self._transfers.get(key)
            if st is not None and (st["total"] != total or st["crc"] != crc):
                # A different payload for this frame: the previous
                # transfer is dead — restart cleanly.
                self._transfers.pop(key, None)
                st = None
            if st is None:
                if off != 0:
                    raise IngestError(
                        409, "unknown transfer; resume from 0", staged=0
                    )
                if len(self._transfers) >= self.max_transfers:
                    self._transfers.pop(next(iter(self._transfers)))
                    self.stats.count("ingest.evicted")
                st = {"total": total, "crc": crc, "off": 0, "rcrc": 0,
                      "ops": 0, "busy": False}
                self._transfers[key] = st
                self.stats.count("ingest.transfers")
            if off + len(body) <= st["off"]:
                # Idempotent re-send of an applied chunk (router WAL
                # replay, client retry): ack the frontier, touch nothing.
                self.stats.count("ingest.resumed")
                return {"staged": st["off"], "done": False, "ops": st["ops"]}
            if off != st["off"]:
                self.stats.count("ingest.gap")
                raise IngestError(
                    409, f"offset gap at {off}; staged={st['off']}",
                    staged=st["off"],
                )
            if st["busy"]:
                raise IngestError(
                    409, "chunk already in flight for this transfer",
                    staged=st["off"],
                )
            st["busy"] = True
        done = False
        ok = False
        try:
            if chunk_crc is not None and zlib.crc32(body) != chunk_crc:
                self.stats.count("ingest.crc_errors")
                raise IngestError(400, "chunk crc mismatch", staged=st["off"])
            if deadline is not None:
                deadline.check("ingest chunk")
            rows, cols = decode_arrow(body) if arrow else decode_packed(body)
            self._apply(key, rows, cols, deadline)
            ok = True
        finally:
            with self._mu:
                st["busy"] = False
                if ok:
                    st["off"] += len(body)
                    st["rcrc"] = zlib.crc32(body, st["rcrc"])
                    st["ops"] += len(rows)
                    self.stats.count("ingest.chunks")
                    self.stats.count("ingest.bytes", len(body))
                    self.stats.count("ingest.ops", len(rows))
                    if st["off"] > total:
                        self._transfers.pop(key, None)
                        raise IngestError(
                            409, "chunk overruns declared total", staged=0
                        )
                    if st["off"] == total:
                        done = True
                        self._transfers.pop(key, None)
                        if st["rcrc"] != crc:
                            # The bits ARE applied (we stream, not
                            # stage); a whole-payload mismatch with
                            # every chunk CRC-clean means the SENDER's
                            # declared CRC is wrong — surface loudly,
                            # the idempotent re-stream converges.
                            self.stats.count("ingest.crc_errors")
                            raise IngestError(
                                409,
                                "payload crc mismatch at completion; "
                                "re-stream to converge",
                                staged=0,
                            )
        if done:
            self.stats.count("ingest.completed")
            if self._complete is not None:
                self._complete(key)
        return {"staged": st["off"], "done": done, "ops": st["ops"]}


@lockcheck.guarded_class
class WriteQueue:
    """Rotating-leader group commit (no dedicated thread, no idle timer)."""

    # Lockset race detector declarations: leadership rotation state and
    # the batch telemetry move under the queue lock (the `_cv` wraps
    # the same ``ingest._mu`` lock object).
    _guarded_by_ = {
        "_committing": "ingest._mu",
        "stat_batches": "ingest._mu",
        "stat_items": "ingest._mu",
    }

    def __init__(self, apply_batch: Callable[[Sequence], list], max_batch: int = 4096):
        self._apply = apply_batch
        self.max_batch = max_batch
        self._mu = lockcheck.named_lock("ingest._mu")
        self._cv = lockcheck.named_condition("ingest._mu", self._mu)
        self._items: list = []  # [(item, slot)]
        self._committing = False
        # Telemetry: batches committed / items seen (bench + tests).
        self.stat_batches = 0
        self.stat_items = 0

    def submit(self, item):
        """Enqueue one item; blocks until its batch commits.  Returns the
        per-item result from apply_batch (raising it if it is an
        exception), or raises the whole batch's error."""
        slot = [False, None, None]  # done, result, exception
        with self._cv:
            self._items.append((item, slot))
            while not slot[0]:
                if not self._committing and self._items:
                    # Leaderless with work pending: this thread commits
                    # exactly ONE batch, then re-checks its own slot —
                    # leadership rotates instead of camping on one thread.
                    self._committing = True
                    batch = self._items[: self.max_batch]
                    del self._items[: len(batch)]
                    self.stat_batches += 1
                    self.stat_items += len(batch)
                    self._mu.release()
                    try:
                        results = self._apply([it for it, _ in batch])
                        for (_, s), r in zip(batch, results):
                            s[1] = r
                            s[0] = True
                    except BaseException as e:  # noqa: BLE001 — poison batch
                        for _, s in batch:
                            s[2] = e
                            s[0] = True
                    finally:
                        self._mu.acquire()
                        self._committing = False
                        self._cv.notify_all()
                    continue
                self._cv.wait()
        if slot[2] is not None:
            raise slot[2]
        if isinstance(slot[1], BaseException):
            raise slot[1]
        return slot[1]
