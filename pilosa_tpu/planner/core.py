"""Lane selection: the ledger-driven half of the executor's strategy ladder.

The executor's fused count paths choose between two strategy families
per working set: the slice-major lane ("gram" — cached row matrix, the
all-pairs Gram and the native serve states it feeds) and the row-major
gather lane ("rmgather" — one contiguous DMA descriptor per operand
row).  The static ladder picks by shape thresholds (gram-rows-max,
``engine.prefer_rowmajor``); this module replaces the pick with a
measured one wherever the ledger has evidence, and reproduces the
static pick bit-for-bit where it doesn't.

Decision contract (the lockstep-safe part):

- ``plan_for(index, body)`` runs at the FRONT DOOR only — the server
  handler per request, the lockstep service on rank 0 at ship time.
  The returned plan dict is JSON-clean and rides ``ExecOptions.plan``
  (single host) or the batch wire entry (lockstep, next to the
  ``expired``/``trace`` flags), so every rank applies the same lane.
- ``plan["lane"] is None`` means "use the static ladder" — the
  executor's decision sites treat it exactly like no plan at all, which
  is what makes an empty ledger reproduce static decisions exactly.
- The executor reports every outcome through :meth:`Planner.record`
  under the lane that ACTUALLY ran (a planner pick vetoed by an
  eligibility gate records as the fallback lane), so mispredictions
  self-correct through the same EWMA fold everything else uses.

Convergence machinery, all deterministic (no RNG — exploration is a
consult-counter modulus, so a replayed request stream re-derives the
same decision sequence):

- confidence gate: a lane only wins on cost once every candidate lane
  has ``min_samples`` observations; until then the static ladder (plus
  exploration ticks) keeps serving.
- exploration: every ``explore_every``-th consult of a key with an
  under-sampled lane returns that lane, so the ledger gains coverage of
  the road not taken without a persistent cost.
- hysteresis: a challenger lane must beat the incumbent's EWMA by
  ``hysteresis`` (fraction) to take over — near-tied lanes don't flap.
- pinning: ``pin`` forces one lane everywhere (the debugging and
  bench-baseline lever; eligibility gates still apply).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Optional

from pilosa_tpu.analysis import lockcheck

# The strategy lanes the planner arbitrates.  Deliberately NOT the
# dispatch-meter lane tags ("gather"/"stream"/"native"): those attribute
# device time to kernels, these name the executor's per-working-set
# strategy families.  Ledger entries for these lanes are written by
# Planner.record only (frame "" — strategy choice is per request shape,
# not per frame), so the two vocabularies coexist in one ledger.
PLAN_LANES = ("gram", "rmgather")

# Bound on distinct (index, fingerprint) keys with live decision state;
# matches the ledger's own LRU philosophy (dashboards repeat a small
# set of shapes).
DEFAULT_KEYS_CAP = 256
DEFAULT_MIN_SAMPLES = 3
DEFAULT_HYSTERESIS = 0.15
DEFAULT_EXPLORE_EVERY = 16


@lockcheck.guarded_class
class Planner:
    """Per-(index, fingerprint) strategy-lane selection over a
    :class:`~pilosa_tpu.costs.CostLedger` (see module docstring)."""

    _guarded_by_ = {"_keys": "planner._mu"}

    def __init__(
        self,
        ledger,
        *,
        min_samples: int = DEFAULT_MIN_SAMPLES,
        hysteresis: float = DEFAULT_HYSTERESIS,
        explore_every: int = DEFAULT_EXPLORE_EVERY,
        pin: str = "",
        keys_cap: int = DEFAULT_KEYS_CAP,
        stats=None,
    ):
        from pilosa_tpu.stats import NOP_STATS

        self.ledger = ledger
        self.min_samples = max(1, int(min_samples))
        self.hysteresis = min(0.9, max(0.0, float(hysteresis)))
        self.explore_every = max(2, int(explore_every))
        self.pin = pin if pin in PLAN_LANES else ""
        self.keys_cap = max(1, int(keys_cap))
        self.stats = stats if stats is not None else NOP_STATS
        self._mu = lockcheck.named_lock("planner._mu")
        # (index, fp) -> {"consults", "incumbent", "decided": {src: n},
        #                 "wins", "losses"} — bounded LRU.
        self._keys: "OrderedDict[tuple[str, str], dict]" = OrderedDict()

    # -- consultation (front door) ----------------------------------------

    def plan_for(self, index: str, body: bytes) -> Optional[dict[str, Any]]:
        """Fingerprint one request body and consult; the JSON-clean plan
        dict for ``ExecOptions.plan`` / the batch wire, or None for
        bodies that don't fingerprint (empty)."""
        if not body:
            return None
        from pilosa_tpu.trace import fingerprint

        return self.choose(index, fingerprint(body)["fp"])

    def choose(self, index: str, fp: str) -> dict[str, Any]:
        """One decision for (index, fp).  Always returns a plan dict —
        ``lane`` None means "static ladder" — so the executor can fold
        the outcome back under the fingerprint either way."""
        if not fp:
            return {"fp": "", "lane": None, "src": "static", "confidence": 0.0}
        with self._mu:
            st = self._keys.get((index, fp))
            if st is None:
                st = self._keys[(index, fp)] = {
                    "consults": 0,
                    "incumbent": None,
                    "decided": {},
                    "wins": 0,
                    "losses": 0,
                }
                while len(self._keys) > self.keys_cap:
                    self._keys.popitem(last=False)
            self._keys.move_to_end((index, fp))
            st["consults"] += 1
            consults = st["consults"]
            incumbent = st["incumbent"]
        lane: Optional[str]
        confidence = 0.0
        if self.pin:
            lane, src = self.pin, "pinned"
            confidence = 1.0
        else:
            costs = {
                ln: self.ledger.peek(index=index, frame="", fp=fp, lane=ln)
                if self.ledger is not None
                else None
                for ln in PLAN_LANES
            }
            counts = {ln: (e["n"] if e else 0) for ln, e in costs.items()}
            confidence = min(
                1.0, min(counts.values()) / float(2 * self.min_samples)
            )
            if all(n >= self.min_samples for n in counts.values()):
                best = min(PLAN_LANES, key=lambda ln: costs[ln]["ewma_ms"])
                if (
                    incumbent in PLAN_LANES
                    and best != incumbent
                    and costs[best]["ewma_ms"]
                    > costs[incumbent]["ewma_ms"] * (1.0 - self.hysteresis)
                ):
                    # Challenger inside the hysteresis band: don't flap.
                    best = incumbent
                lane, src = best, "ledger"
            elif consults % self.explore_every == 0:
                # Deterministic exploration tick: sample the lane the
                # ladder has been starving (ties break in PLAN_LANES
                # order — replicated, no RNG).
                lane = min(PLAN_LANES, key=lambda ln: (counts[ln], PLAN_LANES.index(ln)))
                src = "explore"
            else:
                lane, src = None, "static"
        with self._mu:
            st = self._keys.get((index, fp))
            if st is not None:
                st["decided"][src] = st["decided"].get(src, 0) + 1
                if lane in PLAN_LANES:
                    st["incumbent"] = lane
        self.stats.count(f"planner.choose.{src}")
        return {
            "fp": fp,
            "lane": lane,
            "src": src,
            "confidence": round(confidence, 3),
        }

    # -- fold-back (executor decision sites) ------------------------------

    def record(
        self,
        *,
        index: str,
        fp: str,
        lane: str,
        ms: float,
        plan: Optional[dict] = None,
    ) -> None:
        """Fold one observed dispatch back into the ledger under the
        lane that ACTUALLY ran, and score the decision: a planner-made
        pick (src ledger/explore/pinned) wins when its observed cost
        beats the alternative lane's current EWMA, loses otherwise —
        the /debug/planner win/loss counters and the bench's
        convergence assert both read these."""
        if not fp or lane not in PLAN_LANES:
            return
        other = PLAN_LANES[1 - PLAN_LANES.index(lane)]
        alt = (
            self.ledger.peek(index=index, frame="", fp=fp, lane=other)
            if self.ledger is not None
            else None
        )
        if self.ledger is not None:
            # Rank-0-only state in lockstep (workers carry no planner),
            # like the tracer ring; the wall timestamp is debug payload.
            # analysis-ok: lockstep-determinism: rank-0-only telemetry; lane choices ship on the batch wire
            ts = time.time()
            self.ledger.observe(
                index=index, frame="", fp=fp, lane=lane, ms=ms, wall_ts=ts,
            )
        if plan is None or plan.get("src") not in ("ledger", "explore", "pinned"):
            return
        won = alt is None or ms <= alt["ewma_ms"]
        with self._mu:
            st = self._keys.get((index, fp))
            if st is not None:
                st["wins" if won else "losses"] += 1
        if won:
            self.stats.count(f"planner.win.{lane}")
        else:
            self.stats.count(f"planner.loss.{lane}")

    # -- observability ----------------------------------------------------

    def snapshot(self, limit: int = 0) -> dict:
        """The /debug/planner payload: per-key decision state joined
        with the ledger's per-lane EWMA costs, most-consulted first."""
        with self._mu:
            items = [
                {
                    "index": k[0],
                    "fp": k[1],
                    "incumbent": v["incumbent"],
                    "consults": v["consults"],
                    "decided": dict(v["decided"]),
                    "wins": v["wins"],
                    "losses": v["losses"],
                }
                for k, v in self._keys.items()
            ]
        items.sort(key=lambda e: -e["consults"])
        if limit > 0:
            items = items[:limit]
        for e in items:
            lanes = {}
            for ln in PLAN_LANES:
                ent = (
                    self.ledger.peek(index=e["index"], frame="", fp=e["fp"], lane=ln)
                    if self.ledger is not None
                    else None
                )
                if ent is not None:
                    lanes[ln] = {
                        "n": ent["n"],
                        "ewma_ms": round(ent["ewma_ms"], 3),
                    }
            e["lanes"] = lanes
            counts = [lanes.get(ln, {}).get("n", 0) for ln in PLAN_LANES]
            e["confidence"] = round(
                min(1.0, min(counts) / float(2 * self.min_samples)), 3
            )
        return {
            "lanes": list(PLAN_LANES),
            "min_samples": self.min_samples,
            "hysteresis": self.hysteresis,
            "explore_every": self.explore_every,
            "pin": self.pin,
            "keys": items,
        }
