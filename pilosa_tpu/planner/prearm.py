"""Predictive pre-arming: re-warm hot serve states after writes.

The executor's steady-state serving loop is the armed native lane —
cached row matrix, warm Gram, captured serve state.  An invalidating
write (over the repair budget, or structural) pops that state, and
without this module the NEXT READ pays the rebuild: matrix fetch, Gram
build, state capture, all on a request's critical path.

The PreArmer moves that rebuild off the read path.  The executor's flat
lane registers a REPLAY THUNK per (index, frame) as it serves (the exact
pair arrays of the last flat batch — re-running them re-arms matrix,
Gram, and serve state through the ordinary code path, no special arming
API to keep consistent).  Write paths signal invalidation; a background
worker drains the invalidated keys hottest-first — heat is the measured
serve count since registration, the live analog of the ledger's
hit-rate ranking — re-running each key's thunk TWICE (the Gram warms on
the second touch against an unchanged matrix) under a per-cycle wall
budget, the same throttle shape as the PR-18 bulk materialize drain:
pre-arming must never starve foreground serving.

Single-host only: the lockstep service never constructs one (a
rank-local background replay would run collectives outside the total
order).  Off by default; [planner] prearm-budget-ms enables it.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from pilosa_tpu.analysis import lockcheck

# Bound on registered replay thunks (one per (index, frame) dashboard).
DEFAULT_SHAPES_CAP = 16


@lockcheck.guarded_class
class PreArmer:
    """Budgeted background re-arming of invalidated serve states."""

    _guarded_by_ = {
        "_shapes": "planner.prearm._cv",
        "_pending": "planner.prearm._cv",
    }

    def __init__(self, budget_ms: float = 25.0, shapes_cap: int = DEFAULT_SHAPES_CAP,
                 stats=None):
        from pilosa_tpu.stats import NOP_STATS

        self.budget_ms = max(1.0, float(budget_ms))
        self.shapes_cap = max(1, int(shapes_cap))
        self.stats = stats if stats is not None else NOP_STATS
        self._cv = lockcheck.named_condition("planner.prearm._cv")
        # (index, frame) -> {"thunk": callable, "hits": int} — LRU.
        self._shapes: "OrderedDict[tuple[str, str], dict]" = OrderedDict()
        self._pending: set[tuple[str, str]] = set()
        self._closing = False
        self._thread: threading.Thread | None = None
        # Totals for /debug/vars readers (mirrored as stats counters).
        self.stat_armed = 0
        self.stat_deferred = 0

    # -- executor hooks (serving + write paths) ---------------------------

    def note_shape(self, index: str, frame: str, thunk) -> None:
        """Register/refresh the replay thunk for one (index, frame) and
        count the serve (the heat rank).  Called by the flat lane after
        a successful evaluation — the thunk captures that exact batch."""
        key = (index, frame)
        with self._cv:
            ent = self._shapes.get(key)
            if ent is None:
                ent = self._shapes[key] = {"thunk": thunk, "hits": 0}
                while len(self._shapes) > self.shapes_cap:
                    old, _ = self._shapes.popitem(last=False)
                    self._pending.discard(old)
            else:
                ent["thunk"] = thunk
            ent["hits"] += 1
            self._shapes.move_to_end(key)

    def note_invalidate(self, index: str, frame: str) -> None:
        """A write touched (index, frame): queue a re-arm if the shape
        is known.  Cheap no-op otherwise — every write path calls this."""
        key = (index, frame)
        with self._cv:
            if key in self._shapes and key not in self._pending:
                self._pending.add(key)
                self._cv.notify()

    def forget(self, index: str, frame: str) -> None:
        """Frame dropped: its thunk replays against a dead object graph
        for nothing — discard it."""
        with self._cv:
            self._shapes.pop((index, frame), None)
            self._pending.discard((index, frame))

    def forget_index(self, index: str) -> None:
        with self._cv:
            for k in [k for k in self._shapes if k[0] == index]:
                del self._shapes[k]
            self._pending = {k for k in self._pending if k[0] != index}

    # -- worker -----------------------------------------------------------

    def start(self) -> "PreArmer":
        self._thread = threading.Thread(
            target=self._loop, name="planner-prearm", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        with self._cv:
            self._closing = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _drain_order(self) -> list[tuple[str, str]]:
        """Pending keys hottest-first (must be called with _cv held)."""
        return sorted(
            self._pending,
            key=lambda k: -self._shapes.get(k, {"hits": 0})["hits"],
        )

    def _loop(self) -> None:
        """Drain pending re-arms under the per-cycle budget; past it,
        yield the rest of the interval to foreground serving (deferred
        keys keep their place and drain next cycle)."""
        while True:
            with self._cv:
                while not self._pending and not self._closing:
                    self._cv.wait(timeout=1.0)
                if self._closing:
                    return
                order = self._drain_order()
            t0 = time.perf_counter()
            for key in order:
                with self._cv:
                    ent = self._shapes.get(key)
                    if ent is None or key not in self._pending:
                        continue
                    self._pending.discard(key)
                    thunk = ent["thunk"]
                try:
                    # Twice: the Gram warms on the second touch against
                    # the matrix the first touch re-cached.
                    thunk()
                    thunk()
                except Exception:  # noqa: BLE001 — arming is best-effort
                    # A failed replay (frame dropped mid-flight, engine
                    # hiccup) just means the next read pays cold-start,
                    # the pre-planner behavior; never crash the worker.
                    self.stats.count("planner.prearm_error")
                    continue
                self.stat_armed += 1
                self.stats.count("planner.prearm")
                if (time.perf_counter() - t0) * 1e3 >= self.budget_ms:
                    with self._cv:
                        deferred = len(self._pending)
                    if deferred:
                        self.stat_deferred += deferred
                        self.stats.count("planner.prearm_deferred", deferred)
                    break
            spent_ms = (time.perf_counter() - t0) * 1e3
            self.stats.timing("planner.prearm_ms", spent_ms)
            # Budget pacing: a cycle that spent its budget sleeps the
            # complement, so pre-arming holds a bounded duty cycle.
            if spent_ms >= self.budget_ms:
                time.sleep(self.budget_ms / 1e3)
