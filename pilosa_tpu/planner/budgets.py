"""Adaptive budgets: constants re-derived from measured cost/bandwidth.

Three knobs that were hand-set constants become functions of the cost
ledger, each with the same safety shape: WHILE THE LEDGER IS EMPTY (or
the relevant lanes have no samples) the static configured value is
returned unchanged, and every adaptive value is clamped to a band
around that static default — a poisoned or skewed ledger can shift a
budget, never break it.

- qcache admission floor (``qcache.min-cost-ms``): only results whose
  execution cost clears the floor are cached.  Adaptive form: the 25th
  percentile of observed per-fingerprint EWMA costs — the floor tracks
  the workload's cheap-query population instead of assuming 1 ms means
  "cheap" on every engine.  NOT used by the lockstep service (its
  floor is forced to 0 for determinism).
- replica catch-up drain batch (``CatchupManager.drain_batch``): the
  locked drain phase replays at most this many records under the
  sequencer lock.  Adaptive form: as many records as measured replay
  cost fits in half the locked-drain deadline.
- resync chunk size (``ResyncManager.chunk_bytes``): adaptive form is
  measured push bandwidth times a target per-chunk wall time, so fast
  links stream fewer, larger CRC-framed chunks and slow links keep
  chunks small enough to resume cheaply.

The replica consumers feed their own observations back through
:meth:`AdaptiveBudgets.observe_transfer` (lanes "catchup"/"resync"),
so the router side closes its loop on the data it moves itself.
"""

from __future__ import annotations

from typing import Optional

# Clamp bands and targets (fractions of / multipliers on the static
# defaults; see class docstring for the rationale per budget).
_QCACHE_FLOOR_BAND = (0.1, 10.0)
_QCACHE_MIN_ENTRIES = 8
_RESYNC_TARGET_MS = 50.0
_RESYNC_CHUNK_MIN = 64 << 10
_RESYNC_CHUNK_MAX = 4 << 20
_CATCHUP_BATCH_MIN = 16
_CATCHUP_BATCH_MAX = 1024


class AdaptiveBudgets:
    """Measured-cost replacements for three static budgets (see module
    docstring).  Thread-safe: all state lives in the ledger, which
    locks internally; the derivations are pure reads."""

    def __init__(
        self,
        ledger,
        *,
        qcache_min_cost_ms: float = 1.0,
        catchup_drain_batch: int = 64,
        catchup_locked_drain_s: float = 5.0,
        resync_chunk_bytes: int = 256 << 10,
        stats=None,
    ):
        from pilosa_tpu.stats import NOP_STATS

        self.ledger = ledger
        self.static_qcache_min_cost_ms = float(qcache_min_cost_ms)
        self.static_catchup_drain_batch = int(catchup_drain_batch)
        self.catchup_locked_drain_s = float(catchup_locked_drain_s)
        self.static_resync_chunk_bytes = int(resync_chunk_bytes)
        self.stats = stats if stats is not None else NOP_STATS

    # -- feedback (replica consumers) -------------------------------------

    def observe_transfer(self, lane: str, ms: float, bytes_moved: int = 0) -> None:
        """Fold one transfer observation (catch-up record replay, resync
        chunk push) into the ledger under its budget lane."""
        if self.ledger is not None and ms > 0:
            self.ledger.observe(
                index="", frame="", fp="", lane=lane, ms=ms,
                bytes_moved=bytes_moved,
            )

    # -- derived budgets ---------------------------------------------------

    def _lane(self, lane: str) -> Optional[dict]:
        if self.ledger is None:
            return None
        return self.ledger.peek(index="", frame="", fp="", lane=lane)

    def qcache_min_cost_ms(self) -> float:
        """Admission floor from the observed cost distribution: the 25th
        percentile of per-entry EWMA costs, clamped to [0.1x, 10x] the
        static floor; static until the ledger holds enough entries for
        a percentile to mean anything."""
        static = self.static_qcache_min_cost_ms
        if self.ledger is None or static <= 0:
            return static
        costs = sorted(e["ewma_ms"] for e in self.ledger.entries())
        if len(costs) < _QCACHE_MIN_ENTRIES:
            return static
        p25 = costs[len(costs) // 4]
        lo, hi = _QCACHE_FLOOR_BAND
        floor = min(max(p25, static * lo), static * hi)
        self.stats.gauge("planner.qcache_floor_ms", round(floor, 3))
        return floor

    def catchup_drain_batch(self) -> int:
        """Locked-drain record budget from measured replay cost: fill at
        most HALF the locked-drain deadline at the observed per-record
        EWMA (the other half absorbs variance), clamped; static while
        no replay has ever been measured."""
        static = self.static_catchup_drain_batch
        e = self._lane("catchup")
        if e is None or e["ewma_ms"] <= 0:
            return static
        fit = int((self.catchup_locked_drain_s * 1e3 / 2.0) / e["ewma_ms"])
        batch = min(max(fit, _CATCHUP_BATCH_MIN), _CATCHUP_BATCH_MAX)
        self.stats.gauge("planner.catchup_drain_batch", batch)
        return batch

    def resync_chunk_bytes(self) -> int:
        """Chunk size from measured push bandwidth x the target per-chunk
        wall time, clamped to [64 KiB, 4 MiB]; static until a chunk has
        actually moved bytes."""
        static = self.static_resync_chunk_bytes
        e = self._lane("resync")
        if e is None or e["ewma_mbps"] <= 0:
            return static
        raw = int(e["ewma_mbps"] * 1e6 * (_RESYNC_TARGET_MS / 1e3))
        chunk = min(max(raw, _RESYNC_CHUNK_MIN), _RESYNC_CHUNK_MAX)
        self.stats.gauge("planner.resync_chunk_bytes", chunk)
        return chunk
