"""Cost-based adaptive planner: the control-flow side of the cost ledger.

No reference analog — the reference chooses execution strategies with
build-time constants.  This package closes ROADMAP item 4's feedback
loop: the PR-13 :class:`~pilosa_tpu.costs.CostLedger` stops being pure
telemetry and starts driving decisions.

Three consumers of measured cost, one per module:

- :class:`~pilosa_tpu.planner.core.Planner` — per (index, fingerprint)
  strategy-lane selection for the executor's fused count paths ("gram"
  slice-major family vs "rmgather" row-major gather), confidence-gated
  with hysteresis, every outcome folded back into the ledger.  Decisions
  are made at the FRONT DOOR (server handler, lockstep rank 0) and ride
  ``ExecOptions.plan`` — the executor itself never consults, so lockstep
  workers replay rank 0's plan off the batch wire exactly like expiry
  and sampling flags.
- :class:`~pilosa_tpu.planner.prearm.PreArmer` — hot (index, frame)
  serve states re-armed asynchronously after invalidating writes, under
  a drain budget (the PR-18 bulk-materialize budget pattern), instead of
  paying cold-start on the next read.
- :class:`~pilosa_tpu.planner.budgets.AdaptiveBudgets` — qcache
  admission floor, catch-up drain batch, and resync chunk size derived
  from measured cost/bandwidth instead of constants, each clamped
  around its static default and falling back to it exactly while the
  ledger is empty.

Knobs live in ``config.py`` ([planner] section / PILOSA_TPU_PLANNER_*);
``/debug/planner`` serves decision state.  See DEVELOPMENT.md
("Cost-based adaptive planner").
"""

from pilosa_tpu.planner.budgets import AdaptiveBudgets
from pilosa_tpu.planner.core import PLAN_LANES, Planner
from pilosa_tpu.planner.prearm import PreArmer

__all__ = ["AdaptiveBudgets", "PLAN_LANES", "Planner", "PreArmer"]
