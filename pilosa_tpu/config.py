"""Server configuration.

Reference analog: config.go — TOML `Config` with data dir, host, cluster
section (ReplicaN, type, hosts, internal hosts, polling interval, gossip
seed), anti-entropy interval, max-writes-per-request, log path
(config.go:37-64); defaults port 10101, internal port 14000
(config.go:19-34).  Precedence (cmd/root.go:89-153): flags > env
(PILOSA_*) > TOML file > defaults.
"""

from __future__ import annotations

import os

try:
    import tomllib
except ImportError:  # Python < 3.11: the API-compatible backport
    import tomli as tomllib
from dataclasses import dataclass, field

DEFAULT_HOST = "localhost:10101"
DEFAULT_INTERNAL_PORT = 14000
DEFAULT_ANTI_ENTROPY_INTERVAL = 600.0  # 10 min (server.go:186)
DEFAULT_POLLING_INTERVAL = 60.0  # max-slice poll (server.go:221)
DEFAULT_MAX_WRITES_PER_REQUEST = 5000

CLUSTER_TYPE_STATIC = "static"
CLUSTER_TYPE_HTTP = "http"
CLUSTER_TYPE_GOSSIP = "gossip"


@dataclass
class ClusterConfig:
    replica_n: int = 1
    type: str = CLUSTER_TYPE_STATIC
    hosts: list[str] = field(default_factory=list)
    internal_hosts: list[str] = field(default_factory=list)
    polling_interval: float = DEFAULT_POLLING_INTERVAL
    internal_port: int = DEFAULT_INTERNAL_PORT
    gossip_seed: str = ""


@dataclass
class Config:
    data_dir: str = "~/.pilosa_tpu"
    host: str = DEFAULT_HOST
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    anti_entropy_interval: float = DEFAULT_ANTI_ENTROPY_INTERVAL
    max_writes_per_request: int = DEFAULT_MAX_WRITES_PER_REQUEST
    log_path: str = ""
    engine: str = "auto"
    # "expvar" (default; served at /debug/vars), "statsd[:host[:port]]"
    # (datadog-compatible UDP), "nop" to disable (stats.go:33-54 analog).
    stats: str = "expvar"
    # Executor serve-state LRU capacity: one entry per (index, frame)
    # dashboard kept armed for the single-call native serve lane.  Size
    # for the number of frames a workload alternates between.
    serve_state_cache: int = 4
    # Warm-state repair budget in dirty rows: write bursts touching at
    # most this many distinct rows PATCH the warm serving state (pool
    # row rewrite + rank-k Gram repair) instead of rebuilding it; 0
    # disables repair outright (the bench A/B lever).
    repair_rows_max: int = 64
    # Row ceiling for the cached all-pairs Gram strategy (4096 rows = a
    # 64 MiB Gram; raise on host-attached hardware).
    gram_rows_max: int = 4096
    # -- executor strategy knobs (top-level, like gram-rows-max) ----------
    # These route the executor's remaining raw-env tuning knobs through
    # the one precedence chain (CLI > env > config file > default).  The
    # bare env spellings (PILOSA_TPU_NO_GRAM, _STREAM_BYTES, _SLICE_CHUNK,
    # _MATRIX_CACHE_ENTRIES, _MATRIX_ROWS_MAX) are DEPRECATED: still read
    # by directly-constructed executors, but the configured server passes
    # these fields and new deployments should set them here.
    no_gram: bool = False
    stream_bytes: int = 1 << 31
    slice_chunk: int = 2048
    matrix_cache_entries: int = 4
    matrix_rows_max: int = 1024
    # -- cost-based planner ([planner] TOML section) ----------------------
    # Closes the cost-ledger loop: per-(index, fingerprint) strategy-lane
    # selection from measured EWMA costs (static ladder until confident),
    # background serve-state pre-arming, and ledger-derived budgets.
    # Requires the ledger (PILOSA_TPU_COSTS not disabled) to do anything.
    planner_enabled: bool = True
    # Observations every lane needs before a cost-based pick engages.
    planner_min_samples: int = 3
    # Fractional EWMA advantage a challenger lane must show to displace
    # the incumbent (anti-flap band).
    planner_hysteresis: float = 0.15
    # Every Nth consult of an under-sampled key explores its least-
    # sampled lane (deterministic — a counter modulus, no RNG).
    planner_explore_every: int = 16
    # Pin every decision to one lane ("gram"/"rmgather"); the debugging
    # and bench-baseline lever.  "" = adaptive.
    planner_pin_lane: str = ""
    # Per-cycle wall budget for background serve-state re-arming after
    # invalidating writes; 0 disables the pre-armer (the default: it
    # burns device time speculatively).
    planner_prearm_budget_ms: float = 0.0
    # Derive qcache admission floor / catch-up drain batch / resync chunk
    # size from measured costs instead of their static values.
    planner_adaptive_budgets: bool = True
    # -- HTTP serving ([server] TOML section) -----------------------------
    # Connection worker-pool bound: accepted connections queue to this
    # many pre-spawned handler threads (brief overflow wait, then a
    # 503 + Retry-After shed).  0 = legacy unbounded thread-per-
    # connection.
    server_max_threads: int = 32
    # Multi-process SO_REUSEPORT worker count for GIL builds (the CLI
    # forks N-1 extra server processes sharing one port; free-threaded
    # CPython serves N cores from one process via the pool instead).
    # 0 or 1 = single process.
    server_workers: int = 0
    # -- query result cache ([qcache] TOML section) ----------------------
    # Generation-keyed whole-query result cache in front of the
    # executor: exact (any write to a touched fragment bumps a
    # generation and misses the entry), byte-bounded, cost-admitted.
    qcache_enabled: bool = True
    qcache_max_bytes: int = 256 << 20
    # Admission floor: only results whose measured execution cost is at
    # least this many ms are stored (cheaper requests would pay more in
    # cache bookkeeping than a hit saves).
    qcache_min_cost_ms: float = 1.0
    # -- rank-cache tuning ([cache] TOML section) ------------------------
    # Debounce on RankCache invalidation (ranked TopN caches recalculate
    # at most once per this many seconds; cache.go:219-226's hard-coded
    # 10 s, promoted).
    ranking_debounce_s: float = 10.0
    # -- request-lifecycle QoS ([qos] TOML section) ----------------------
    # Default per-request time budget in ms when the client sends no
    # X-Pilosa-Deadline-Ms header; 0 = unbounded (pre-QoS behavior).
    default_deadline_ms: float = 0.0
    # Per-class admission depths (max concurrently executing requests;
    # an equal number may wait briefly at the door).  0 = unbounded.
    qos_read_depth: int = 64
    qos_write_depth: int = 32
    qos_admin_depth: int = 16
    # How long a request may wait at a full door before shedding, and
    # the Retry-After hint returned with a 429/503.
    qos_queue_wait_ms: float = 100.0
    qos_retry_after_ms: float = 250.0
    # -- request tracing ([trace] TOML section) --------------------------
    # Head-sampling rate for the request-scoped span tracer (0.0 = only
    # X-Pilosa-Trace-forced requests trace; 1.0 = every request).
    trace_sample_rate: float = 0.0
    # Slow-query threshold in ms: requests slower than this land in the
    # /debug/traces ring REGARDLESS of sampling and emit one structured
    # line on the pilosa_tpu.slowquery logger.  0 = disabled.
    trace_slow_ms: float = 0.0
    # Bounded in-memory ring of finished traces served at /debug/traces.
    trace_ring: int = 256
    # -- replicated serving groups ([replica] TOML section) --------------
    # This server's serving-group identity ("g0" or "g0@3" with an
    # explicit epoch) behind the replica router; "" = not in a group.
    replica_group: str = ""
    # Router: the group front doors to fan over ("host:port" or
    # "name=host:port"; names default to g0, g1, ...).
    replica_groups: list[str] = field(default_factory=list)
    # Router bind port (the front door clients talk to).
    replica_router_port: int = 10111
    # One-shot read failover to a sibling group on connect/5xx failure
    # (reads are side-effect-free, so the retry is always safe).
    replica_failover: bool = True
    # Health-probe cadence for down/lagging groups: the base interval,
    # doubled (with jitter) per failed probe up to the cap and reset on
    # recovery — a dead group is not hammered in lockstep by every
    # router.
    replica_probe_interval: float = 1.0
    replica_probe_max_interval: float = 30.0
    # Router write-ahead log directory ("" = in-memory: same sequence /
    # abort / replay semantics, no crash durability) and the backlog
    # bound: a laggard that would pin the log past wal-max-bytes is
    # declared stale (operator resync) instead of growing it unbounded.
    replica_wal_dir: str = ""
    replica_wal_max_bytes: int = 64 << 20
    # Cross-group anti-entropy sweep interval in seconds (jittered;
    # 0 = off, the default — tests and single-group rigs don't want a
    # background digest walker).  Healthy groups' content digests are
    # compared and any silently diverged fragment is repaired from the
    # majority copy.
    replica_anti_entropy_interval: float = 0.0
    # Chunk size of the resync fragment stream (each chunk CRC-framed
    # and individually acked, so a killed transfer resumes at the
    # staged offset).
    replica_resync_chunk_bytes: int = 256 << 10
    # Columnar resync negotiation: movers may fetch a fragment the
    # laggard lacks entirely as Arrow record batches (donor
    # /export?format=arrow) and push it through the laggard's
    # device-build /bulk door; any refusal degrades to the roaring
    # byte stream.  Off by default — both sides must speak the PR-18
    # bulk wire for the fast path to engage.
    replica_resync_columnar: bool = False
    # Partitioned replica groups (the 2-D slice-shard x replica mesh).
    # shards = N splits the flat group list into N consecutive chunks,
    # shard i owning slices [i*shard-span, (i+1)*shard-span) (last
    # open-ended); shard-map is the explicit form
    # ("s0=0-4:g0=h:p,g1=h:p;s1=4-:g2=h:p,g3=h:p") and wins over
    # shards when both are set.  1 + "" = the single-shard default:
    # byte-for-byte the pre-shard router.
    replica_shards: int = 1
    replica_shard_map: str = ""
    replica_shard_span: int = 256
    # -- streaming columnar ingest ([ingest] TOML section) ----------------
    # Per-chunk byte ceiling at the streaming bulk-ingest door
    # (POST /index/<i>/frame/<f>/ingest): a chunk past it answers 413
    # instead of buffering an unbounded request body.
    ingest_chunk_bytes: int = 4 << 20
    # -- device bulk build ([bulk] TOML section) --------------------------
    # Slice planes committed per fragment batch at the bulk build door
    # (POST /index/<i>/frame/<f>/bulk): bounds the per-commit lock hold
    # and the transient plane allocation, like gram-rows-max bounds the
    # Gram working set.
    bulk_batch_slices: int = 8
    # Time budget (ms) for the opportunistic overlay->roaring drain at
    # bulk transfer completion.  0 = fully lazy: containers materialize
    # only on a roaring-shaped touch (snapshot/digest/mutation/export).
    bulk_materialize_budget_ms: float = 0.0
    # -- HTTP client ([client] TOML section) ------------------------------
    # Retry budget for door sheds (429/503 — both issued BEFORE any
    # execution, so writes are safe to retry): total extra attempts per
    # logical request, deadline-aware, decorrelated-jitter backoff.
    client_retry_budget: int = 2
    # -- lockstep service ([lockstep] TOML section) ----------------------
    # Rank-0 wait for a worker's receipt ack (control-plane latency +
    # scheduling, not execution) and a worker's connect retry window at
    # startup — both previously hard-coded in parallel/service.py.
    lockstep_ack_timeout: float = 120.0
    lockstep_connect_timeout: float = 60.0
    # Bound on rank 0's arrival queue: requests beyond this shed with
    # 429 instead of growing the coalescing queue without limit.
    lockstep_queue_depth: int = 256
    # -- multi-tenant isolation ([tenancy] TOML section) ------------------
    # Off by default: every enforcement seam (admission doors, qcache,
    # ingest pacer) takes its pre-tenancy path byte-identically.
    tenancy_enabled: bool = False
    # "gold=4,free=1" — fair-share weights; unlisted tenants get
    # default-weight.
    tenancy_weights: str = ""
    tenancy_default_weight: float = 1.0
    # "idx_a=gold,idx_b=free" — explicit index→tenant table; unmapped
    # indexes bill to their own name.
    tenancy_map: str = ""
    # qcache byte quota: a bare fraction ("0.5") applied to every
    # tenant, or per-tenant overrides ("gold=0.75,free=0.1").  Empty =
    # no per-tenant cache quota.
    tenancy_qcache_share: str = ""
    # Aggregate ingest/bulk chunk bandwidth split by weight across
    # active tenants; 0 disables the pacer.
    tenancy_ingest_bytes_per_s: int = 0

    @classmethod
    def from_toml(cls, path: str) -> "Config":
        with open(path, "rb") as f:
            raw = tomllib.load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: dict) -> "Config":
        cfg = cls()
        cfg.data_dir = raw.get("data-dir", cfg.data_dir)
        cfg.host = raw.get("host", cfg.host)
        cfg.anti_entropy_interval = _interval(
            raw.get("anti-entropy", {}).get("interval"), cfg.anti_entropy_interval
        )
        cfg.max_writes_per_request = raw.get(
            "max-writes-per-request", cfg.max_writes_per_request
        )
        cfg.log_path = raw.get("log-path", cfg.log_path)
        cfg.engine = raw.get("engine", cfg.engine)
        cfg.stats = raw.get("stats", cfg.stats)
        cfg.serve_state_cache = int(
            raw.get("serve-state-cache", cfg.serve_state_cache)
        )
        cfg.repair_rows_max = int(raw.get("repair-rows-max", cfg.repair_rows_max))
        cfg.gram_rows_max = int(raw.get("gram-rows-max", cfg.gram_rows_max))
        cfg.no_gram = bool(raw.get("no-gram", cfg.no_gram))
        cfg.stream_bytes = int(raw.get("stream-bytes", cfg.stream_bytes))
        cfg.slice_chunk = int(raw.get("slice-chunk", cfg.slice_chunk))
        cfg.matrix_cache_entries = int(
            raw.get("matrix-cache-entries", cfg.matrix_cache_entries)
        )
        cfg.matrix_rows_max = int(raw.get("matrix-rows-max", cfg.matrix_rows_max))
        pl = raw.get("planner", {})
        cfg.planner_enabled = bool(pl.get("enabled", cfg.planner_enabled))
        cfg.planner_min_samples = int(pl.get("min-samples", cfg.planner_min_samples))
        cfg.planner_hysteresis = float(pl.get("hysteresis", cfg.planner_hysteresis))
        cfg.planner_explore_every = int(
            pl.get("explore-every", cfg.planner_explore_every)
        )
        cfg.planner_pin_lane = str(pl.get("pin-lane", cfg.planner_pin_lane))
        cfg.planner_prearm_budget_ms = float(
            pl.get("prearm-budget-ms", cfg.planner_prearm_budget_ms)
        )
        cfg.planner_adaptive_budgets = bool(
            pl.get("adaptive-budgets", cfg.planner_adaptive_budgets)
        )
        srv = raw.get("server", {})
        cfg.server_max_threads = int(srv.get("max-threads", cfg.server_max_threads))
        cfg.server_workers = int(srv.get("workers", cfg.server_workers))
        qc = raw.get("qcache", {})
        cfg.qcache_enabled = bool(qc.get("enabled", cfg.qcache_enabled))
        cfg.qcache_max_bytes = int(qc.get("max-bytes", cfg.qcache_max_bytes))
        cfg.qcache_min_cost_ms = float(qc.get("min-cost-ms", cfg.qcache_min_cost_ms))
        cache = raw.get("cache", {})
        cfg.ranking_debounce_s = _interval(
            cache.get("ranking-debounce-s"), cfg.ranking_debounce_s
        )
        qos = raw.get("qos", {})
        cfg.default_deadline_ms = 1000.0 * _interval(
            qos.get("default-deadline"), cfg.default_deadline_ms / 1000.0
        )
        cfg.qos_read_depth = int(qos.get("read-depth", cfg.qos_read_depth))
        cfg.qos_write_depth = int(qos.get("write-depth", cfg.qos_write_depth))
        cfg.qos_admin_depth = int(qos.get("admin-depth", cfg.qos_admin_depth))
        cfg.qos_queue_wait_ms = 1000.0 * _interval(
            qos.get("queue-wait"), cfg.qos_queue_wait_ms / 1000.0
        )
        cfg.qos_retry_after_ms = 1000.0 * _interval(
            qos.get("retry-after"), cfg.qos_retry_after_ms / 1000.0
        )
        tr = raw.get("trace", {})
        cfg.trace_sample_rate = float(tr.get("sample-rate", cfg.trace_sample_rate))
        cfg.trace_slow_ms = float(tr.get("slow-ms", cfg.trace_slow_ms))
        cfg.trace_ring = int(tr.get("ring", cfg.trace_ring))
        rep = raw.get("replica", {})
        cfg.replica_group = str(rep.get("group", cfg.replica_group))
        cfg.replica_groups = list(rep.get("groups", cfg.replica_groups))
        cfg.replica_router_port = int(rep.get("router-port", cfg.replica_router_port))
        cfg.replica_failover = bool(rep.get("failover", cfg.replica_failover))
        cfg.replica_probe_interval = _interval(
            rep.get("probe-interval"), cfg.replica_probe_interval
        )
        cfg.replica_probe_max_interval = _interval(
            rep.get("probe-max-interval"), cfg.replica_probe_max_interval
        )
        cfg.replica_wal_dir = str(rep.get("wal-dir", cfg.replica_wal_dir))
        cfg.replica_wal_max_bytes = int(
            rep.get("wal-max-bytes", cfg.replica_wal_max_bytes)
        )
        cfg.replica_anti_entropy_interval = _interval(
            rep.get("anti-entropy-interval"), cfg.replica_anti_entropy_interval
        )
        cfg.replica_resync_chunk_bytes = int(
            rep.get("resync-chunk-bytes", cfg.replica_resync_chunk_bytes)
        )
        cfg.replica_resync_columnar = bool(
            rep.get("resync-columnar", cfg.replica_resync_columnar)
        )
        cfg.replica_shards = int(rep.get("shards", cfg.replica_shards))
        cfg.replica_shard_map = str(rep.get("shard-map", cfg.replica_shard_map))
        cfg.replica_shard_span = int(
            rep.get("shard-span", cfg.replica_shard_span)
        )
        ing = raw.get("ingest", {})
        cfg.ingest_chunk_bytes = int(ing.get("chunk-bytes", cfg.ingest_chunk_bytes))
        blk = raw.get("bulk", {})
        cfg.bulk_batch_slices = int(blk.get("batch-slices", cfg.bulk_batch_slices))
        cfg.bulk_materialize_budget_ms = float(
            blk.get("materialize-budget-ms", cfg.bulk_materialize_budget_ms)
        )
        cli = raw.get("client", {})
        cfg.client_retry_budget = int(
            cli.get("retry-budget", cfg.client_retry_budget)
        )
        ls = raw.get("lockstep", {})
        cfg.lockstep_ack_timeout = _interval(
            ls.get("ack-timeout"), cfg.lockstep_ack_timeout
        )
        cfg.lockstep_connect_timeout = _interval(
            ls.get("connect-timeout"), cfg.lockstep_connect_timeout
        )
        cfg.lockstep_queue_depth = int(
            ls.get("queue-depth", cfg.lockstep_queue_depth)
        )
        ten = raw.get("tenancy", {})
        cfg.tenancy_enabled = bool(ten.get("enabled", cfg.tenancy_enabled))
        cfg.tenancy_weights = str(ten.get("weights", cfg.tenancy_weights))
        cfg.tenancy_default_weight = float(
            ten.get("default-weight", cfg.tenancy_default_weight)
        )
        cfg.tenancy_map = str(ten.get("map", cfg.tenancy_map))
        cfg.tenancy_qcache_share = str(
            ten.get("qcache-share", cfg.tenancy_qcache_share)
        )
        cfg.tenancy_ingest_bytes_per_s = int(
            ten.get("ingest-bytes-per-s", cfg.tenancy_ingest_bytes_per_s)
        )
        cl = raw.get("cluster", {})
        cfg.cluster.replica_n = cl.get("replicas", cfg.cluster.replica_n)
        cfg.cluster.type = cl.get("type", cfg.cluster.type)
        cfg.cluster.hosts = list(cl.get("hosts", cfg.cluster.hosts))
        cfg.cluster.internal_hosts = list(cl.get("internal-hosts", cfg.cluster.internal_hosts))
        cfg.cluster.polling_interval = _interval(
            cl.get("polling-interval"), cfg.cluster.polling_interval
        )
        cfg.cluster.internal_port = cl.get("internal-port", cfg.cluster.internal_port)
        cfg.cluster.gossip_seed = cl.get("gossip-seed", cfg.cluster.gossip_seed)
        return cfg

    def apply_env(self, env=None) -> "Config":
        """PILOSA_* environment overrides (cmd/root.go:118-134 analog)."""
        env = env if env is not None else os.environ
        self.data_dir = env.get("PILOSA_DATA_DIR", self.data_dir)
        self.host = env.get("PILOSA_HOST", self.host)
        if "PILOSA_CLUSTER_HOSTS" in env:
            self.cluster.hosts = [h.strip() for h in env["PILOSA_CLUSTER_HOSTS"].split(",") if h.strip()]
        if "PILOSA_CLUSTER_REPLICAS" in env:
            self.cluster.replica_n = int(env["PILOSA_CLUSTER_REPLICAS"])
        if "PILOSA_CLUSTER_TYPE" in env:
            self.cluster.type = env["PILOSA_CLUSTER_TYPE"]
        if "PILOSA_ENGINE" in env:
            self.engine = env["PILOSA_ENGINE"]
        if "PILOSA_STATS" in env:
            self.stats = env["PILOSA_STATS"]
        if "PILOSA_SERVE_STATE_CACHE" in env:
            self.serve_state_cache = int(env["PILOSA_SERVE_STATE_CACHE"])
        if "PILOSA_TPU_REPAIR_ROWS_MAX" in env:
            self.repair_rows_max = int(env["PILOSA_TPU_REPAIR_ROWS_MAX"])
        if "PILOSA_TPU_GRAM_ROWS_MAX" in env:
            self.gram_rows_max = int(env["PILOSA_TPU_GRAM_ROWS_MAX"])
        if "PILOSA_TPU_NO_GRAM" in env:
            self.no_gram = env["PILOSA_TPU_NO_GRAM"].lower() in ("1", "true", "yes")
        if "PILOSA_TPU_STREAM_BYTES" in env:
            self.stream_bytes = int(env["PILOSA_TPU_STREAM_BYTES"])
        if "PILOSA_TPU_SLICE_CHUNK" in env:
            self.slice_chunk = int(env["PILOSA_TPU_SLICE_CHUNK"])
        if "PILOSA_TPU_MATRIX_CACHE_ENTRIES" in env:
            self.matrix_cache_entries = int(env["PILOSA_TPU_MATRIX_CACHE_ENTRIES"])
        if "PILOSA_TPU_MATRIX_ROWS_MAX" in env:
            self.matrix_rows_max = int(env["PILOSA_TPU_MATRIX_ROWS_MAX"])
        if "PILOSA_TPU_PLANNER" in env:
            self.planner_enabled = env["PILOSA_TPU_PLANNER"].lower() in (
                "1", "true", "yes",
            )
        if "PILOSA_TPU_PLANNER_MIN_SAMPLES" in env:
            self.planner_min_samples = int(env["PILOSA_TPU_PLANNER_MIN_SAMPLES"])
        if "PILOSA_TPU_PLANNER_HYSTERESIS" in env:
            self.planner_hysteresis = float(env["PILOSA_TPU_PLANNER_HYSTERESIS"])
        if "PILOSA_TPU_PLANNER_EXPLORE_EVERY" in env:
            self.planner_explore_every = int(env["PILOSA_TPU_PLANNER_EXPLORE_EVERY"])
        if "PILOSA_TPU_PLANNER_PIN_LANE" in env:
            self.planner_pin_lane = env["PILOSA_TPU_PLANNER_PIN_LANE"]
        if "PILOSA_TPU_PLANNER_PREARM_BUDGET_MS" in env:
            self.planner_prearm_budget_ms = float(
                env["PILOSA_TPU_PLANNER_PREARM_BUDGET_MS"]
            )
        if "PILOSA_TPU_PLANNER_ADAPTIVE_BUDGETS" in env:
            self.planner_adaptive_budgets = env[
                "PILOSA_TPU_PLANNER_ADAPTIVE_BUDGETS"
            ].lower() in ("1", "true", "yes")
        if "PILOSA_TPU_SERVER_MAX_THREADS" in env:
            self.server_max_threads = int(env["PILOSA_TPU_SERVER_MAX_THREADS"])
        if "PILOSA_TPU_SERVER_WORKERS" in env:
            self.server_workers = int(env["PILOSA_TPU_SERVER_WORKERS"])
        if "PILOSA_TPU_QCACHE" in env:
            self.qcache_enabled = env["PILOSA_TPU_QCACHE"].lower() in ("1", "true", "yes")
        if "PILOSA_TPU_QCACHE_MAX_BYTES" in env:
            self.qcache_max_bytes = int(env["PILOSA_TPU_QCACHE_MAX_BYTES"])
        if "PILOSA_TPU_QCACHE_MIN_COST_MS" in env:
            self.qcache_min_cost_ms = float(env["PILOSA_TPU_QCACHE_MIN_COST_MS"])
        if "PILOSA_TPU_RANKING_DEBOUNCE_S" in env:
            self.ranking_debounce_s = float(env["PILOSA_TPU_RANKING_DEBOUNCE_S"])
        if "PILOSA_TPU_DEADLINE_MS" in env:
            self.default_deadline_ms = float(env["PILOSA_TPU_DEADLINE_MS"])
        if "PILOSA_TPU_QOS_READ_DEPTH" in env:
            self.qos_read_depth = int(env["PILOSA_TPU_QOS_READ_DEPTH"])
        if "PILOSA_TPU_QOS_WRITE_DEPTH" in env:
            self.qos_write_depth = int(env["PILOSA_TPU_QOS_WRITE_DEPTH"])
        if "PILOSA_TPU_QOS_ADMIN_DEPTH" in env:
            self.qos_admin_depth = int(env["PILOSA_TPU_QOS_ADMIN_DEPTH"])
        if "PILOSA_TPU_QOS_QUEUE_WAIT_MS" in env:
            self.qos_queue_wait_ms = float(env["PILOSA_TPU_QOS_QUEUE_WAIT_MS"])
        if "PILOSA_TPU_QOS_RETRY_AFTER_MS" in env:
            self.qos_retry_after_ms = float(env["PILOSA_TPU_QOS_RETRY_AFTER_MS"])
        if "PILOSA_TPU_TRACE_SAMPLE_RATE" in env:
            self.trace_sample_rate = float(env["PILOSA_TPU_TRACE_SAMPLE_RATE"])
        if "PILOSA_TPU_TRACE_SLOW_MS" in env:
            self.trace_slow_ms = float(env["PILOSA_TPU_TRACE_SLOW_MS"])
        if "PILOSA_TPU_TRACE_RING" in env:
            self.trace_ring = int(env["PILOSA_TPU_TRACE_RING"])
        if "PILOSA_TPU_REPLICA_GROUP" in env:
            self.replica_group = env["PILOSA_TPU_REPLICA_GROUP"]
        if "PILOSA_TPU_REPLICA_GROUPS" in env:
            self.replica_groups = [
                g.strip() for g in env["PILOSA_TPU_REPLICA_GROUPS"].split(",")
                if g.strip()
            ]
        if "PILOSA_TPU_REPLICA_ROUTER_PORT" in env:
            self.replica_router_port = int(env["PILOSA_TPU_REPLICA_ROUTER_PORT"])
        if "PILOSA_TPU_REPLICA_FAILOVER" in env:
            self.replica_failover = env["PILOSA_TPU_REPLICA_FAILOVER"].lower() in (
                "1", "true", "yes",
            )
        if "PILOSA_TPU_REPLICA_PROBE_INTERVAL" in env:
            self.replica_probe_interval = float(
                env["PILOSA_TPU_REPLICA_PROBE_INTERVAL"]
            )
        if "PILOSA_TPU_REPLICA_PROBE_MAX_INTERVAL" in env:
            self.replica_probe_max_interval = float(
                env["PILOSA_TPU_REPLICA_PROBE_MAX_INTERVAL"]
            )
        if "PILOSA_TPU_REPLICA_WAL_DIR" in env:
            self.replica_wal_dir = env["PILOSA_TPU_REPLICA_WAL_DIR"]
        if "PILOSA_TPU_REPLICA_WAL_MAX_BYTES" in env:
            self.replica_wal_max_bytes = int(env["PILOSA_TPU_REPLICA_WAL_MAX_BYTES"])
        if "PILOSA_TPU_REPLICA_ANTI_ENTROPY_INTERVAL" in env:
            self.replica_anti_entropy_interval = float(
                env["PILOSA_TPU_REPLICA_ANTI_ENTROPY_INTERVAL"]
            )
        if "PILOSA_TPU_REPLICA_RESYNC_CHUNK_BYTES" in env:
            self.replica_resync_chunk_bytes = int(
                env["PILOSA_TPU_REPLICA_RESYNC_CHUNK_BYTES"]
            )
        if "PILOSA_TPU_REPLICA_RESYNC_COLUMNAR" in env:
            self.replica_resync_columnar = env[
                "PILOSA_TPU_REPLICA_RESYNC_COLUMNAR"
            ].lower() in ("1", "true", "yes")
        if "PILOSA_TPU_REPLICA_SHARDS" in env:
            self.replica_shards = int(env["PILOSA_TPU_REPLICA_SHARDS"])
        if "PILOSA_TPU_REPLICA_SHARD_MAP" in env:
            self.replica_shard_map = env["PILOSA_TPU_REPLICA_SHARD_MAP"]
        if "PILOSA_TPU_REPLICA_SHARD_SPAN" in env:
            self.replica_shard_span = int(env["PILOSA_TPU_REPLICA_SHARD_SPAN"])
        if "PILOSA_TPU_INGEST_CHUNK_BYTES" in env:
            self.ingest_chunk_bytes = int(env["PILOSA_TPU_INGEST_CHUNK_BYTES"])
        if "PILOSA_TPU_BULK_BATCH_SLICES" in env:
            self.bulk_batch_slices = int(env["PILOSA_TPU_BULK_BATCH_SLICES"])
        if "PILOSA_TPU_BULK_MATERIALIZE_BUDGET_MS" in env:
            self.bulk_materialize_budget_ms = float(
                env["PILOSA_TPU_BULK_MATERIALIZE_BUDGET_MS"]
            )
        if "PILOSA_TPU_CLIENT_RETRY_BUDGET" in env:
            self.client_retry_budget = int(env["PILOSA_TPU_CLIENT_RETRY_BUDGET"])
        if "PILOSA_TPU_LOCKSTEP_ACK_TIMEOUT" in env:
            self.lockstep_ack_timeout = float(env["PILOSA_TPU_LOCKSTEP_ACK_TIMEOUT"])
        if "PILOSA_TPU_LOCKSTEP_CONNECT_TIMEOUT" in env:
            self.lockstep_connect_timeout = float(
                env["PILOSA_TPU_LOCKSTEP_CONNECT_TIMEOUT"]
            )
        if "PILOSA_TPU_LOCKSTEP_QUEUE_DEPTH" in env:
            self.lockstep_queue_depth = int(env["PILOSA_TPU_LOCKSTEP_QUEUE_DEPTH"])
        if "PILOSA_TPU_TENANCY" in env:
            self.tenancy_enabled = env["PILOSA_TPU_TENANCY"].lower() in (
                "1", "true", "yes",
            )
        if "PILOSA_TPU_TENANCY_WEIGHTS" in env:
            self.tenancy_weights = env["PILOSA_TPU_TENANCY_WEIGHTS"]
        if "PILOSA_TPU_TENANCY_DEFAULT_WEIGHT" in env:
            self.tenancy_default_weight = float(
                env["PILOSA_TPU_TENANCY_DEFAULT_WEIGHT"]
            )
        if "PILOSA_TPU_TENANCY_MAP" in env:
            self.tenancy_map = env["PILOSA_TPU_TENANCY_MAP"]
        if "PILOSA_TPU_TENANCY_QCACHE_SHARE" in env:
            self.tenancy_qcache_share = env["PILOSA_TPU_TENANCY_QCACHE_SHARE"]
        if "PILOSA_TPU_TENANCY_INGEST_BYTES_PER_S" in env:
            self.tenancy_ingest_bytes_per_s = int(
                env["PILOSA_TPU_TENANCY_INGEST_BYTES_PER_S"]
            )
        return self

    def to_toml(self) -> str:
        lines = [
            f'data-dir = "{self.data_dir}"',
            f'host = "{self.host}"',
            f'stats = "{self.stats}"',
            "",
            "[cluster]",
            f'  type = "{self.cluster.type}"',
            f"  replicas = {self.cluster.replica_n}",
            f"  hosts = [{', '.join(repr(h) for h in self.cluster.hosts)}]".replace("'", '"'),
            f"  internal-port = {self.cluster.internal_port}",
            "",
            "[anti-entropy]",
            f'  interval = "{int(self.anti_entropy_interval)}s"',
        ]
        return "\n".join(lines) + "\n"


def _interval(v, default: float) -> float:
    """Parse '10m'/'600s'/number into seconds."""
    if v is None:
        return default
    if isinstance(v, (int, float)):
        return float(v)
    v = str(v).strip()
    try:
        if v.endswith("ms"):
            return float(v[:-2]) / 1000
        if v.endswith("s") and not v.endswith("ms"):
            return float(v[:-1])
        if v.endswith("m"):
            return float(v[:-1]) * 60
        if v.endswith("h"):
            return float(v[:-1]) * 3600
        return float(v)
    except ValueError:
        return default
