"""Holder: the root of the data hierarchy, owning all indexes on disk.

Reference analog: holder.go — opens the data directory, discovers indexes
from subdirectories (holder.go:73-121), exposes Schema() (holder.go:154),
accessor chain Holder → Index → Frame → View → Fragment
(holder.go:298-322), and the periodic rank-cache flush (holder.go:324-358,
driven by the server loop here).

Path layout matches the reference
(<data>/<index>/<frame>/views/<view>/fragments/<slice>; holder.go:174).
"""

from __future__ import annotations

import os
import shutil
import threading

from pilosa_tpu.analysis import lockcheck
from typing import Optional

from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.frame import Frame
from pilosa_tpu.core.index import Index, IndexOptions
from pilosa_tpu.core.view import View
from pilosa_tpu.pilosa import ErrIndexExists, ErrIndexNotFound, validate_name

CACHE_FLUSH_INTERVAL = 60.0  # seconds (holder.go:30-31)


class Holder:
    def __init__(self, path: str, stats=None, ranking_debounce_s=None):
        from pilosa_tpu.stats import NopStatsClient

        self.path = path
        self.stats = stats if stats is not None else NopStatsClient()
        # [cache] ranking-debounce-s, threaded down through Index ->
        # Frame -> View -> Fragment -> RankCache; None = module default.
        self.ranking_debounce_s = ranking_debounce_s
        # Guards index create/delete against concurrent schema merges
        # (gossip push/pull runs from two threads; holder.go:35 mu analog).
        self._mu = lockcheck.named_rlock("core.holder._mu")
        self.indexes: dict[str, Index] = {}
        # Hook invoked as (index, frame, view, slice) when a fragment for a
        # new max slice is created locally — the server broadcasts a
        # CreateSliceMessage from it (view.go:219-254).
        self.on_new_fragment = None

    # -- lifecycle ------------------------------------------------------

    def open(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        for entry in sorted(os.listdir(self.path)):
            full = os.path.join(self.path, entry)
            if not os.path.isdir(full) or entry.startswith("."):
                continue
            idx = Index(
                full,
                entry,
                stats=self.stats.with_tags(f"index:{entry}"),
                on_new_fragment=self._fragment_hook,
                ranking_debounce_s=self.ranking_debounce_s,
            )
            idx.open()
            self.indexes[entry] = idx
            self.stats.count("indexN", 1)  # holder.go:113

    def close(self) -> None:
        for idx in list(self.indexes.values()):
            idx.close()
        self.indexes.clear()

    def _fragment_hook(self, index: str, frame: str, view: str, slice_i: int) -> None:
        if self.on_new_fragment is not None:
            self.on_new_fragment(index, frame, view, slice_i)

    def flush_caches(self) -> None:
        # list() snapshots: schema merges may insert concurrently
        for idx in list(self.indexes.values()):
            idx.flush_caches()

    # -- indexes ---------------------------------------------------------

    def index(self, name: str) -> Optional[Index]:
        return self.indexes.get(name)

    def create_index(self, name: str, opt: Optional[IndexOptions] = None) -> Index:
        with self._mu:
            if name in self.indexes:
                raise ErrIndexExists(name)
            return self._create_index(name, opt or IndexOptions())

    def create_index_if_not_exists(self, name: str, opt: Optional[IndexOptions] = None) -> Index:
        with self._mu:
            idx = self.indexes.get(name)
            if idx is not None:
                return idx
            return self._create_index(name, opt or IndexOptions())

    def _create_index(self, name: str, opt: IndexOptions) -> Index:
        validate_name(name)
        # Validate options BEFORE any directory exists (no ghost indexes).
        opt.validate()
        idx = Index(
            os.path.join(self.path, name),
            name,
            stats=self.stats.with_tags(f"index:{name}"),
            on_new_fragment=self._fragment_hook,
            ranking_debounce_s=self.ranking_debounce_s,
        )
        idx.open()
        idx.apply_options(opt)
        self.indexes[name] = idx
        self.stats.count("indexN", 1)  # holder.go:252
        return idx

    def delete_index(self, name: str) -> None:
        # close + rmtree stay under the lock so a concurrent create of the
        # same name can't have its fresh directory deleted out from under it.
        with self._mu:
            idx = self.indexes.pop(name, None)
            if idx is None:
                raise ErrIndexNotFound(name)
            idx.close()
            shutil.rmtree(idx.path, ignore_errors=True)
            self.stats.count("indexN", -1)  # holder.go:292

    # -- accessors (holder.go:298-322) ------------------------------------

    def frame(self, index: str, frame: str) -> Optional[Frame]:
        idx = self.index(index)
        return idx.frame(frame) if idx else None

    def view(self, index: str, frame: str, view: str) -> Optional[View]:
        f = self.frame(index, frame)
        return f.view(view) if f else None

    def fragment(self, index: str, frame: str, view: str, slice_i: int) -> Optional[Fragment]:
        v = self.view(index, frame, view)
        return v.fragment(slice_i) if v else None

    # -- schema (holder.go:154-171) ---------------------------------------

    def schema(self) -> list[dict]:
        return [idx.schema_json() for _, idx in sorted(list(self.indexes.items()))]

    def max_slices(self) -> dict[str, int]:
        return {name: idx.max_slice() for name, idx in list(self.indexes.items())}

    def max_inverse_slices(self) -> dict[str, int]:
        return {name: idx.max_inverse_slice() for name, idx in list(self.indexes.items())}
