"""Index: a namespace of frames sharing a column space.

Reference analog: index.go.  Owns the column AttrStore, the columnLabel
(default "columnID", index.go:34), a default time quantum inherited by new
frames, and ``remote_max_slice`` — the cluster-wide max slice learned from
peers so queries span slices this node has never written
(index.go:252-272).
"""

from __future__ import annotations

import json
import os
import threading

from pilosa_tpu.analysis import lockcheck
from typing import Optional

from pilosa_tpu.core import timequantum as tq
from pilosa_tpu.core.attr import AttrStore
from pilosa_tpu.core.frame import Frame, FrameOptions
from pilosa_tpu.pilosa import (
    ErrColumnRowLabelEqual,
    ErrFrameExists,
    ErrFrameNotFound,
    validate_label,
    validate_name,
)

DEFAULT_COLUMN_LABEL = "columnID"


class IndexOptions:
    def __init__(self, column_label: str = "", time_quantum: str = ""):
        self.column_label = column_label
        self.time_quantum = time_quantum

    def validate(self) -> None:
        """Raise for any invalid option — checked BEFORE creating index
        state on disk, so a rejected create leaves no ghost index."""
        if self.column_label:
            validate_label(self.column_label)
        if self.time_quantum:
            tq.parse_time_quantum(self.time_quantum)


class Index:
    def __init__(
        self,
        path: str,
        name: str,
        stats=None,
        on_new_fragment=None,
        ranking_debounce_s=None,
    ):
        from pilosa_tpu.stats import NopStatsClient

        validate_name(name)
        self.path = path
        self.name = name
        self.stats = stats if stats is not None else NopStatsClient()
        self.on_new_fragment = on_new_fragment
        self.ranking_debounce_s = ranking_debounce_s

        self.column_label = DEFAULT_COLUMN_LABEL
        self.time_quantum = ""
        self.remote_max_slice = 0
        self.remote_max_inverse_slice = 0

        # Guards frame create/delete against concurrent schema merges
        # (index.go mu analog).
        self._mu = lockcheck.named_rlock("core.index._mu")
        self.frames: dict[str, Frame] = {}
        self.column_attr_store = AttrStore(os.path.join(path, "column_attrs.db"))

    # -- lifecycle ------------------------------------------------------

    def open(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        self._load_meta()
        self.column_attr_store.open()
        for entry in sorted(os.listdir(self.path)):
            full = os.path.join(self.path, entry)
            if not os.path.isdir(full) or entry.startswith("."):
                continue
            frame = Frame(
                full,
                self.name,
                entry,
                stats=self.stats.with_tags(f"frame:{entry}"),
                on_new_fragment=self.on_new_fragment,
                ranking_debounce_s=self.ranking_debounce_s,
            )
            frame.open()
            self.frames[entry] = frame
            self.stats.count("frameN", 1)  # index.go:183

    def close(self) -> None:
        self.column_attr_store.close()
        for f in list(self.frames.values()):
            f.close()
        self.frames.clear()

    def flush_caches(self) -> None:
        # list() snapshots: schema merges may insert concurrently
        for f in list(self.frames.values()):
            f.flush_caches()

    @property
    def meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def _load_meta(self) -> None:
        try:
            with open(self.meta_path) as f:
                meta = json.load(f)
        except FileNotFoundError:
            return
        self.column_label = meta.get("columnLabel", DEFAULT_COLUMN_LABEL)
        self.time_quantum = meta.get("timeQuantum", "")

    def save_meta(self) -> None:
        with open(self.meta_path, "w") as f:
            json.dump({"columnLabel": self.column_label, "timeQuantum": self.time_quantum}, f)

    def apply_options(self, opt: IndexOptions) -> None:
        # Callers validate first (Holder._create_index runs opt.validate()
        # BEFORE any on-disk state exists); this only applies.
        if opt.column_label:
            self.column_label = opt.column_label
        if opt.time_quantum:
            self.time_quantum = tq.parse_time_quantum(opt.time_quantum)
        self.save_meta()

    def set_time_quantum(self, q: str) -> None:
        self.time_quantum = tq.parse_time_quantum(q)
        self.save_meta()

    # -- slices ---------------------------------------------------------

    def max_slice(self) -> int:
        """Max of local frames and the remotely-observed max (index.go:252)."""
        local = max((f.max_slice() for f in list(self.frames.values())), default=0)
        return max(local, self.remote_max_slice)

    def max_inverse_slice(self) -> int:
        local = max((f.max_inverse_slice() for f in list(self.frames.values())), default=0)
        return max(local, self.remote_max_inverse_slice)

    def set_remote_max_slice(self, v: int) -> None:
        self.remote_max_slice = max(self.remote_max_slice, v)

    def set_remote_max_inverse_slice(self, v: int) -> None:
        self.remote_max_inverse_slice = max(self.remote_max_inverse_slice, v)

    # -- frames ----------------------------------------------------------

    def frame(self, name: str) -> Optional[Frame]:
        return self.frames.get(name)

    def create_frame(self, name: str, opt: FrameOptions) -> Frame:
        with self._mu:
            if name in self.frames:
                raise ErrFrameExists(name)
            return self._create_frame(name, opt)

    def create_frame_if_not_exists(self, name: str, opt: Optional[FrameOptions] = None) -> Frame:
        with self._mu:
            f = self.frames.get(name)
            if f is not None:
                return f
            return self._create_frame(name, opt or FrameOptions())

    def _create_frame(self, name: str, opt: FrameOptions) -> Frame:
        validate_name(name)
        # Frame row label may not equal the index column label
        # (index.go:386-388) — the query arg namespace would collide.
        row_label = opt.row_label or "rowID"
        if row_label == self.column_label:
            raise ErrColumnRowLabelEqual(f"row label equals column label: {row_label}")
        # Validate ALL options BEFORE any directory exists: a rejected
        # create must not leave a ghost frame that reappears on restart.
        opt.validate()
        frame = Frame(
            os.path.join(self.path, name),
            self.name,
            name,
            stats=self.stats.with_tags(f"frame:{name}"),
            on_new_fragment=self.on_new_fragment,
            ranking_debounce_s=self.ranking_debounce_s,
        )
        frame.open()
        if not opt.time_quantum and self.time_quantum:
            opt.time_quantum = self.time_quantum  # inherit index default
        frame.apply_options(opt)
        self.frames[name] = frame
        self.stats.count("frameN", 1)  # index.go:434
        return frame

    def delete_frame(self, name: str) -> None:
        import shutil

        # close + rmtree stay under the lock so a concurrent create of the
        # same name can't have its fresh directory deleted out from under it.
        with self._mu:
            f = self.frames.pop(name, None)
            if f is None:
                raise ErrFrameNotFound(name)
            self.stats.count("frameN", -1)  # index.go:474
            f.close()
            shutil.rmtree(f.path, ignore_errors=True)

    def schema_json(self) -> dict:
        return {
            "name": self.name,
            "columnLabel": self.column_label,
            "timeQuantum": self.time_quantum,
            "frames": [f.schema_json() for _, f in sorted(list(self.frames.items()))],
        }
