"""Host-side data model: fragments, views, frames, indexes, holder, caches.

Reference analogs: fragment.go, view.go, frame.go, index.go, holder.go,
cache.go, attr.go, time.go.  This layer owns durability (snapshot + WAL),
the directory layout, and the metadata hierarchy; the compute-heavy query
path lives in pilosa_tpu.ops (device kernels) and pilosa_tpu.executor.
"""
