"""Time-quantum view decomposition.

Reference analog: time.go.  A timestamped bit is written into one view per
quantum unit (Y/M/D/H, time.go:82-92); a range query covers [start, end)
with the minimal set of unit views — walk up from small to large units
until aligned, then back down (time.go:95-167).

This is the reference's "long-axis" scaling trick for the time dimension
(SURVEY.md §5): on the TPU side each time view is just another stack of
slice-sharded bitmaps, and a Range query becomes a segmented OR-reduction
over the covering views.
"""

from __future__ import annotations

from datetime import datetime, timedelta

from pilosa_tpu.pilosa import ErrInvalidTimeQuantum

_VALID = {"Y", "YM", "YMD", "YMDH", "M", "MD", "MDH", "D", "DH", "H", ""}

_FMT = {"Y": "%Y", "M": "%Y%m", "D": "%Y%m%d", "H": "%Y%m%d%H"}


def parse_time_quantum(v: str) -> str:
    q = v.upper()
    if q not in _VALID:
        raise ErrInvalidTimeQuantum(f"invalid time quantum: {v!r}")
    return q


def view_by_time_unit(name: str, t: datetime, unit: str) -> str:
    fmt = _FMT.get(unit)
    if fmt is None:
        return ""
    return f"{name}_{t.strftime(fmt)}"


def views_by_time(name: str, t: datetime, quantum: str) -> list[str]:
    """One view name per unit in the quantum (write fan-out; time.go:82-92)."""
    return [v for unit in quantum if (v := view_by_time_unit(name, t, unit))]


def _add_date(t: datetime, years: int, months: int, days: int) -> datetime:
    """Calendar add with Go time.AddDate overflow normalization
    (Jan 31 + 1 month = Mar 2/3, matching Go's semantics)."""
    y = t.year + years
    m = t.month + months
    y += (m - 1) // 12
    m = (m - 1) % 12 + 1
    base = t.replace(year=y, month=m, day=1)
    return base + timedelta(days=t.day - 1 + days)


def _next_year_gte(t: datetime, end: datetime) -> bool:
    next_t = _add_date(t, 1, 0, 0)
    return next_t.year == end.year or end > next_t


def _next_month_gte(t: datetime, end: datetime) -> bool:
    next_t = _add_date(t, 0, 1, 0)
    return (next_t.year, next_t.month) == (end.year, end.month) or end > next_t


def _next_day_gte(t: datetime, end: datetime) -> bool:
    next_t = _add_date(t, 0, 0, 1)
    return next_t.date() == end.date() or end > next_t


def views_by_time_range(name: str, start: datetime, end: datetime, quantum: str) -> list[str]:
    """Minimal view cover of [start, end) (time.go:95-167)."""
    has_y, has_m, has_d, has_h = ("Y" in quantum, "M" in quantum, "D" in quantum, "H" in quantum)
    t = start
    results: list[str] = []

    # Walk up small→large: emit sub-unit views until t is aligned to the
    # next-larger unit (or the range can't reach that unit's boundary).
    if has_h or has_d or has_m:
        while t < end:
            if has_h:
                if not _next_day_gte(t, end):
                    break
                if t.hour != 0:
                    results.append(view_by_time_unit(name, t, "H"))
                    t = t + timedelta(hours=1)
                    continue
            if has_d:
                if not _next_month_gte(t, end):
                    break
                if t.day != 1:
                    results.append(view_by_time_unit(name, t, "D"))
                    t = _add_date(t, 0, 0, 1)
                    continue
            if has_m:
                if not _next_year_gte(t, end):
                    break
                if t.month != 1:
                    results.append(view_by_time_unit(name, t, "M"))
                    t = _add_date(t, 0, 1, 0)
                    continue
            break

    # Walk down large→small consuming whole units that fit.
    while t < end:
        if has_y and _next_year_gte(t, end):
            results.append(view_by_time_unit(name, t, "Y"))
            t = _add_date(t, 1, 0, 0)
        elif has_m and _next_month_gte(t, end):
            results.append(view_by_time_unit(name, t, "M"))
            t = _add_date(t, 0, 1, 0)
        elif has_d and _next_day_gte(t, end):
            results.append(view_by_time_unit(name, t, "D"))
            t = _add_date(t, 0, 0, 1)
        elif has_h:
            results.append(view_by_time_unit(name, t, "H"))
            t = t + timedelta(hours=1)
        else:
            break

    return results
