"""Row-rank caches and Pair merge for TopN.

Reference analog: cache.go — the Cache interface (cache.go:35-52), LRUCache
(cache.go:55-123), RankCache with threshold trimming + 10s invalidation
debounce (cache.go:126-275), SimpleCache (cache.go:438-462), and the
Pairs.Add distributed-TopN merge (cache.go:343-361).

Observable semantics preserved (SURVEY.md §7 hard part (c)): ThresholdFactor
1.1 buffer, threshold = count of the (maxEntries+1)-th ranked entry, 10s
debounce on invalidate, trim of entries at-or-below threshold when the map
outgrows the buffer.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Iterable

THRESHOLD_FACTOR = 1.1

# RankCache invalidation debounce (cache.go:219-226's hard-coded 10 s,
# promoted to config).  The configured value ([cache] ranking-debounce-s,
# env-resolved once in Config._apply_env) threads through Holder ->
# Index -> Frame -> View -> Fragment construction; an absent ctor arg
# falls back to this module default — no module-global mutation, so two
# servers in one process never leak each other's setting.
DEFAULT_RANKING_DEBOUNCE_S = 10.0

# Cache type names (frame.go:33-40).
CACHE_TYPE_LRU = "lru"
CACHE_TYPE_RANKED = "ranked"
DEFAULT_CACHE_TYPE = CACHE_TYPE_LRU


@dataclass(frozen=True)
class Pair:
    """(row id, count) result pair (cache.go:291-294)."""

    id: int
    count: int

    def to_json(self) -> dict:
        return {"id": self.id, "count": self.count}


def pairs_add(a: Iterable[Pair], b: Iterable[Pair]) -> list[Pair]:
    """Merge counts by id (distributed TopN reduce; cache.go:343-361)."""
    m: dict[int, int] = {}
    for p in a:
        m[p.id] = m.get(p.id, 0) + p.count
    for p in b:
        m[p.id] = m.get(p.id, 0) + p.count
    return [Pair(id=k, count=v) for k, v in m.items()]


def pairs_sorted(pairs: Iterable[Pair]) -> list[Pair]:
    """Descending by count, then ascending id for determinism."""
    return sorted(pairs, key=lambda p: (-p.count, p.id))


class LRUCache:
    """LRU row-count cache (cache.go:55-123)."""

    def __init__(self, max_entries: int):
        self.max_entries = max_entries
        self._od: OrderedDict[int, int] = OrderedDict()

    def add(self, id: int, n: int) -> None:
        self._od[id] = n
        self._od.move_to_end(id)
        while len(self._od) > self.max_entries:
            self._od.popitem(last=False)

    bulk_add = add

    def get(self, id: int) -> int:
        n = self._od.get(id, 0)
        if id in self._od:
            self._od.move_to_end(id)
        return n

    def __len__(self) -> int:
        return len(self._od)

    def ids(self) -> list[int]:
        return sorted(self._od.keys())

    def invalidate(self) -> None:
        pass

    def recalculate(self) -> None:
        pass

    def top(self) -> list[Pair]:
        return pairs_sorted(Pair(id=k, count=v) for k, v in self._od.items() if v > 0)


class RankCache:
    """Ranked row cache with entry threshold (cache.go:126-275).

    Keeps up to ``max_entries`` top rows by count plus a slop buffer;
    ``threshold_value`` is the count of the first evicted rank, and adds
    below it are ignored.  ``invalidate`` is debounced to once per
    ``debounce_s`` (default 10 s, cache.go:219-226; config
    ``[cache] ranking-debounce-s`` / PILOSA_TPU_RANKING_DEBOUNCE_S,
    resolved in Config and threaded through holder construction);
    ``recalculate`` forces it.
    """

    def __init__(self, max_entries: int, _now=time.monotonic, debounce_s=None):
        self.max_entries = max_entries
        self.threshold_buffer = int(THRESHOLD_FACTOR * max_entries)
        self.threshold_value = 0
        self.entries: dict[int, int] = {}
        self.rankings: list[Pair] = []
        if debounce_s is None:
            debounce_s = DEFAULT_RANKING_DEBOUNCE_S
        self.debounce_s = float(debounce_s)
        self._now = _now
        self._update_time = _now() - 1e9

    def add(self, id: int, n: int) -> None:
        if n < self.threshold_value:
            return
        self.entries[id] = n
        self.invalidate()

    def bulk_add(self, id: int, n: int) -> None:
        """Unsorted add; caller should invalidate()/recalculate() after."""
        if n < self.threshold_value:
            return
        self.entries[id] = n

    def get(self, id: int) -> int:
        return self.entries.get(id, 0)

    def __len__(self) -> int:
        return len(self.entries)

    def ids(self) -> list[int]:
        return sorted(self.entries.keys())

    def invalidate(self) -> None:
        if self._now() - self._update_time < self.debounce_s:
            return
        self.recalculate()

    def recalculate(self) -> None:
        rankings = pairs_sorted(Pair(id=k, count=v) for k, v in self.entries.items())
        if len(rankings) > self.max_entries:
            self.threshold_value = rankings[self.max_entries].count
            rankings = rankings[: self.max_entries]
        else:
            self.threshold_value = 1
        self.rankings = rankings
        self._update_time = self._now()
        if len(self.entries) > self.threshold_buffer:
            self.entries = {
                k: v for k, v in self.entries.items() if v > self.threshold_value
            }

    def top(self) -> list[Pair]:
        return self.rankings


class SimpleCache:
    """Unbounded id->count map (cache.go:438-462 BitmapCache/SimpleCache)."""

    def __init__(self):
        self.entries: dict[int, int] = {}

    def add(self, id: int, n: int) -> None:
        self.entries[id] = n

    bulk_add = add

    def get(self, id: int) -> int:
        return self.entries.get(id, 0)

    def __len__(self) -> int:
        return len(self.entries)

    def ids(self) -> list[int]:
        return sorted(self.entries.keys())

    def invalidate(self) -> None:
        pass

    def recalculate(self) -> None:
        pass

    def top(self) -> list[Pair]:
        return pairs_sorted(Pair(id=k, count=v) for k, v in self.entries.items() if v > 0)


def new_cache(cache_type: str, size: int, ranking_debounce_s=None):
    if cache_type == CACHE_TYPE_RANKED:
        return RankCache(size, debounce_s=ranking_debounce_s)
    if cache_type == CACHE_TYPE_LRU:
        return LRUCache(size)
    if cache_type in ("", "simple", "none"):
        return SimpleCache()
    from pilosa_tpu.pilosa import ErrInvalidCacheType

    raise ErrInvalidCacheType(f"invalid cache type: {cache_type}")
