"""Fragment: the (index, frame, view, slice) unit of storage.

Reference analog: fragment.go (1514 LoC).  A fragment owns one slice of one
view's bitmap matrix: bit ``(rowID, columnID)`` lives at linear position
``pos = rowID*SLICE_WIDTH + columnID % SLICE_WIDTH`` (fragment.go:1512-1514)
inside a roaring bitmap, persisted as snapshot-file + appended WAL ops with
re-snapshot after MaxOpN=2000 ops (fragment.go:63-65, 993-1057).

TPU-first departures from the reference:

- Row reads surface as dense packed ``uint32[SLICE_WIDTH/32]`` word arrays
  (``row_dense``), the exact layout the device kernels consume; the roaring
  form is only touched at the storage boundary.
- TopN's per-candidate ``Src.IntersectionCount(f.Row(id))`` scalar loop
  (fragment.go:553-560) becomes chunked *batched* popcount counts over a
  stacked candidate matrix (`_batch_intersection_counts`) — same results,
  same threshold-pruning semantics, but the hot loop is one vectorized
  call per chunk instead of K scalar loops, so the executor can push it
  through the fused TPU kernel.
"""

from __future__ import annotations

import hashlib
import itertools
import math
import os
import tempfile
import threading

from pilosa_tpu.analysis import lockcheck
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from pilosa_tpu import native as native_mod
from pilosa_tpu import roaring
from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.ops import bitwise as bw
from pilosa_tpu.pilosa import ErrFragmentClosed, ErrFragmentLocked, SLICE_WIDTH

try:
    import fcntl
except ImportError:  # non-POSIX: no inter-process lock (reference is
    fcntl = None  # POSIX-only here too: syscall.Flock, fragment.go:187)

# Number of rows in a checksum block (fragment.go:59 HashBlockSize).
HASH_BLOCK_SIZE = 100

# Snapshot after this many WAL ops (fragment.go:63-65 DefaultFragmentMaxOpN).
DEFAULT_MAX_OPN = 2000

DEFAULT_CACHE_SIZE = 50000

# TopN candidate-scoring chunk; engine scorers pad to this for stable
# jitted shapes, so both sites must share the constant.
TOPN_SCORE_CHUNK = 256

_WORDS = SLICE_WIDTH // 32

# Process-global write-generation source (see Fragment.generation).
_generation_counter = itertools.count(1)

# Read-only singleton changed-vectors for the scalar write-lane path
# (np.full costs ~0.7 us per singleton request).
_CH_TRUE = np.full(1, True, dtype=bool)
_CH_TRUE.setflags(write=False)
_CH_FALSE = np.full(1, False, dtype=bool)
_CH_FALSE.setflags(write=False)

# Dirty-row journal length (entries, one per generation bump).  Past this
# the oldest entries are dropped and deltas reaching back that far become
# unenumerable (rows_dirty_since returns None -> callers rebuild), which
# is exactly the right degradation: a warm cache that fell thousands of
# writes behind is not worth patching row by row anyway.
_DIRTY_LOG_MAX = int(os.environ.get("PILOSA_TPU_DIRTY_LOG_MAX", "512"))

# Magic header for the sidecar .cache file (row-id list persisted so ranked
# caches can be rebuilt by recount on open; fragment.go:236-274, 1073-1093).
_CACHE_MAGIC = b"PTPC\x01"


@dataclass
class TopOptions:
    """Options for Fragment.top (fragment.go:662-677)."""

    n: int = 0
    src: Optional[roaring.Bitmap] = None
    # Pre-densified src (uint32[W] slice-local words); the executor's batched
    # path passes this directly so the device-evaluated child bitmap never
    # round-trips through a roaring conversion.
    src_dense: Optional[np.ndarray] = None
    # Optional batched scorer: callable(list[row_id]) -> int array of
    # |row & src| per id, or None to decline a chunk (the fragment then
    # scores it with its own host path).  The executor passes an
    # engine-backed one so the candidate hot loop (fragment.go:553-560)
    # runs on device against the cached HBM row matrix.
    scorer: Optional[object] = None
    row_ids: Sequence[int] = field(default_factory=list)
    min_threshold: int = 0
    filter_field: str = ""
    filter_values: Sequence = field(default_factory=list)
    tanimoto_threshold: int = 0

    @property
    def has_src(self) -> bool:
        return self.src is not None or self.src_dense is not None


def _batch_intersection_counts(rows: np.ndarray, src: np.ndarray) -> np.ndarray:
    """|rows[k] & src| per row; numpy host path (device path in executor)."""
    return bw.np_popcount(rows & src).reshape(rows.shape[0], -1).sum(axis=1)


@lockcheck.guarded_class
class Fragment:
    """One slice of one view's row-major bitmap matrix."""

    # Lockset race detector declarations (PILOSA_TPU_LOCK_CHECK=1):
    # every post-init REBIND of these fields must hold the fragment
    # lock.  Storage identity and the write generation are the validity
    # tokens every warm cache (serve states, row pools, qcache vectors,
    # armed write-lane tables) hangs off — an unguarded write here is
    # how a free-threaded host serves stale or torn state.
    _guarded_by_ = {
        "storage": "core.fragment._mu",
        "generation": "core.fragment._mu",
        "_wal": "core.fragment._mu",
        "_open": "core.fragment._mu",
        "_storage_map": "core.fragment._mu",
        "_writelane": "core.fragment._mu",
        "_writelane_streak": "core.fragment._mu",
        "_writelane_cooldown": "core.fragment._mu",
        "_pending_rows": "core.fragment._mu",
        "_bulk_planes": "core.fragment._mu",
        "_checksum_cache": "core.fragment._mu",
        "_opn_trigger": "core.fragment._mu",
        "_dirty_floor": "core.fragment._mu",
    }

    def __init__(
        self,
        path: str,
        index: str,
        frame: str,
        view: str,
        slice_i: int,
        cache_type: str = cache_mod.DEFAULT_CACHE_TYPE,
        cache_size: int = DEFAULT_CACHE_SIZE,
        max_opn: int = DEFAULT_MAX_OPN,
        row_attr_store=None,
        stats=None,
        ranking_debounce_s=None,
    ):
        self.path = path
        self.index = index
        self.frame = frame
        self.view = view
        self.slice = slice_i
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.ranking_debounce_s = ranking_debounce_s
        self.max_opn = max_opn
        from pilosa_tpu.stats import NOP_STATS

        self.row_attr_store = row_attr_store
        self.stats = stats if stats is not None else NOP_STATS

        # Guards storage + caches against concurrent readers/writers
        # (fragment.go:69 mu analog).
        self._mu = lockcheck.named_rlock("core.fragment._mu")
        self.storage: roaring.Bitmap = roaring.Bitmap()
        self.cache = cache_mod.new_cache(cache_type, cache_size, ranking_debounce_s)
        self._wal = None  # append handle to the data file
        self._row_cache: OrderedDict[int, np.ndarray] = OrderedDict()
        self._row_cache_max = 64
        # Device-resident dense rows (HBM working set): per row id, a dict
        # of engine-name -> engine array, so repeat queries skip the
        # host→device upload entirely and mutation invalidates a row in
        # O(1) (one dict pop, not a scan over the cache).  The bound counts
        # ARRAYS (rows x engines), keeping the same memory cap as the old
        # flat (engine, row) keying even when several engines read one
        # fragment.
        self._row_dev_cache: OrderedDict[int, dict] = OrderedDict()
        self._row_dev_cache_max = 256
        self._row_dev_cache_arrays = 0
        self._checksums: dict[int, bytes] = {}
        # Whole-fragment checksum memo keyed by write generation (the
        # replica digest protocol hashes every fragment per sweep; an
        # unwritten fragment answers from here without re-walking its
        # blocks).  Generation-keyed, so no mutator needs to clear it.
        self._checksum_cache: Optional[tuple[int, bytes]] = None
        # Incrementally-maintained per-row bit counts (LRU-bounded like the
        # other per-row caches): every guarded mutation knows its delta, so
        # the rank-cache update on the SetBit hot path avoids a count_range
        # scan per op (fragment.go keeps the same invariant through its
        # stored container counts).
        self._row_counts: OrderedDict[int, int] = OrderedDict()
        self._row_counts_max = 4096
        # Deferred (row -> bit-count delta) bookkeeping from the ingest
        # hot path; drained by _flush_row_bookkeeping before cache reads.
        self._pending_rows: dict[int, int] = {}
        # Pending dense overlay from the device bulk builder: row id ->
        # packed uint32[SLICE_WIDTH/32] word plane OF BITS NOT YET IN
        # STORAGE's roaring form.  Serving reads merge it for free
        # (row_dense ORs word planes); roaring-shaped touches (snapshot,
        # digest, WAL-logged mutation, export of containers) MUST call
        # _materialize_bulk_locked first so storage is always the full
        # truth wherever its container structure is observed.  The
        # bulk.lazy ledger tracks fragments with a non-empty overlay.
        self._bulk_planes: dict[int, np.ndarray] = {}
        self._open = False
        self._max_opn_scale: Optional[int] = None  # lazy env read
        self._opn_trigger = 0  # cached snapshot trigger (_increment_opn)
        self._lock_fd: Optional[int] = None
        self._storage_map = None  # live mmap backing zero-copy containers
        # Write generation: refreshed on every mutation from a
        # process-global counter, so engine-side assembled row matrices
        # (executor fused path) can validate their cache without hashing
        # storage.  Global (not per-object) so a deleted+recreated
        # fragment can never repeat an old fragment's generation and
        # revive its cache entries.
        self.generation = next(_generation_counter)
        # Dirty-row journal: one (generation, rows) entry per generation
        # bump, so warm device state (executor serve states, row-pool
        # matrices, Grams) can be PATCHED after small writes instead of
        # rebuilt (rows None = unenumerable bulk change).  The floor is
        # the creation generation: a consumer holding an older fragment's
        # generation can never enumerate a delta against this one.
        self._dirty_log: "list[tuple[int, Optional[tuple[int, ...]]]]" = []
        self._dirty_floor = self.generation
        # Armed container table for the native write request lane
        # (write_batch): sorted container keys + slack-buffer addresses/
        # counts/capacities handed to pn_write_batch so one GIL-released
        # crossing can do parse + insert + WAL for a whole batch.  Valid
        # only while (storage identity, generation) match — any foreign
        # writer or snapshot swap invalidates it by construction.
        self._writelane: Optional[dict] = None
        # Adaptive disarm: when structural declines dominate (cold
        # uniform workloads where most ops first-touch a container),
        # the native crossing is pure overhead — idle the lane for a
        # stretch and let the plain Python lanes serve, re-probing
        # periodically.
        self._writelane_streak = 0
        self._writelane_cooldown = 0

    # -- lifecycle (fragment.go:151-274) --------------------------------

    def open(self) -> None:
        if self._open:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._acquire_flock()
        # A crash between the snapshot temp write and the rename leaves an
        # orphaned .snapshotting file; the data file is still the previous
        # good state (os.replace is atomic), so just sweep the orphans.
        import glob

        for stale in glob.glob(glob.escape(self.path) + ".*.snapshotting"):
            try:
                os.unlink(stale)
            except OSError:
                pass
        try:
            if os.path.exists(self.path):
                data, mm = self._map_storage()
                if data is not None:
                    try:
                        self.storage = roaring.Bitmap.from_bytes(
                            data, zero_copy=mm is not None
                        )
                    except ValueError:
                        # Torn WAL tail (crash mid-append): recover the
                        # valid prefix and truncate the file there.  Real
                        # snapshot-body corruption re-raises from inside
                        # from_bytes_recover's strict body parse.  Safe
                        # with the mmap: valid_len covers the snapshot
                        # body, so no container view extends past the
                        # truncation point.
                        self.storage, valid_len = roaring.Bitmap.from_bytes_recover(
                            data, zero_copy=mm is not None
                        )
                        with open(self.path, "r+b") as f:
                            f.truncate(valid_len)
                        self.stats.count("walRecoveredN", 1)
                    self._storage_map = mm
            self._attach_wal()
            self._load_cache()
        except BaseException:
            if self._wal is not None:  # mirror close(): no fd leak, and no
                self._wal.close()  # live append handle past the lock release
                self._wal = None
                self.storage.op_writer = None
            self._release_flock()
            raise
        self._open = True

    @staticmethod
    def _mmap_enabled() -> bool:
        # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
        return os.environ.get("PILOSA_TPU_MMAP", "1").lower() not in (
            "0", "false", "no",
        )

    def _map_storage(self):
        """(buffer, mmap-or-None) for the storage file: an mmap when
        possible (zero-copy attach: open cost is O(container headers),
        payloads page in on demand, the index can exceed host RAM —
        fragment.go:179-234), else the file bytes.  ``PILOSA_TPU_MMAP=0``
        forces the read path."""
        if self._mmap_enabled():
            import mmap as _mmap

            try:
                with open(self.path, "rb") as f:
                    mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
                if hasattr(mm, "madvise"):
                    # The query access pattern is random container touches
                    # (the reference's MADV_RANDOM, fragment.go:205).
                    mm.madvise(_mmap.MADV_RANDOM)
                return mm, mm
            except (OSError, ValueError):
                pass  # empty file or fs without mmap: fall through
        with open(self.path, "rb") as f:
            data = f.read()
        return (data if data else None), None

    def close(self) -> None:
        with self._mu:
            # Pay any bulk-overlay debt FIRST, while the WAL is still
            # attached: the conversion logs op records (or snapshots),
            # and a detach-then-materialize would silently drop them.
            if self._open and self._bulk_planes:
                self._materialize_bulk_locked()
        with self._mu:
            if self._wal is not None:
                # Detach + close UNDER the write lock: the fused native
                # add caches the raw fd from op_writer and write(2)s to
                # it with the GIL released — closing outside _mu could
                # free the fd (reusable by any later open()) while an
                # in-flight add still writes to it.  Detaching first
                # also resets the Bitmap's fd cache (op_writer setter).
                self.storage.op_writer = None
                self._wal.close()
                self._wal = None
        with self._mu:
            self._flush_row_bookkeeping()
            # Flip _open UNDER the lock, before any storage swap below:
            # a concurrent guarded caller that acquires _mu after this
            # point raises ErrFragmentClosed instead of racing the swap
            # (the TOCTOU would let e.g. snapshot() rewrite the data
            # file from the swapped-in empty bitmap).
            self._open = False
        self._save_cache()
        self._release_flock()
        # Drop the storage containers BEFORE closing the map: mmap.close()
        # with live exported views would fail (BufferError) — replace
        # storage so no view outlives the mapping.  Under _mu so a reader
        # mid-query (e.g. delete_frame closing while a row read holds the
        # lock) never observes the swapped-in empty bitmap.
        mm = getattr(self, "_storage_map", None)
        if mm is not None:
            with self._mu:
                self.storage = roaring.Bitmap()
                self._storage_map = None
            try:
                mm.close()
            except BufferError:
                pass  # a caller still holds a row view; GC will finish it

    def _acquire_flock(self) -> None:
        """Exclusive inter-process lock for this fragment's files.

        The reference flocks the storage file itself for the process
        lifetime (fragment.go:179-234).  Here snapshots replace the data
        file by rename, which would silently break inode-based lock
        continuity, so the lock lives on a ``.lock`` sidecar whose inode
        never changes.  Non-blocking: a second opener fails immediately
        (ErrFragmentLocked) instead of corrupting a shared data dir.
        """
        if fcntl is None:
            return
        import errno

        fd = os.open(self.path + ".lock", os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            os.close(fd)
            if e.errno in (errno.EWOULDBLOCK, errno.EAGAIN, errno.EACCES):
                raise ErrFragmentLocked(
                    f"fragment file locked by another process: {self.path}"
                )
            if e.errno in (errno.ENOLCK, errno.EOPNOTSUPP, errno.ENOTSUP):
                # Filesystem can't do flock (some NFS mounts): degrade to
                # unlocked operation rather than bricking every open with
                # a misleading "locked by another process".
                return
            raise  # real I/O error: surface as-is
        self._lock_fd = fd

    def _release_flock(self) -> None:
        fd = getattr(self, "_lock_fd", None)
        if fd is not None:
            self._lock_fd = None
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            finally:
                os.close(fd)

    def _attach_wal(self) -> None:
        if self._wal is not None:
            self._wal.close()
        if not os.path.exists(self.path):
            with open(self.path, "wb") as f:
                self.storage.write_to(f)
            self.storage.op_n = 0
        # Unbuffered: each op record reaches the kernel immediately, like the
        # reference's direct file writes (a buffered handle would lose acked
        # ops on crash).
        self._wal = open(self.path, "ab", buffering=0)
        self.storage.op_writer = self._wal
        self._opn_trigger = 0  # storage swap: recompute on next op

    @property
    def cache_path(self) -> str:
        return self.path + ".cache"

    def _load_cache(self) -> None:
        try:
            with open(self.cache_path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return
        if not data.startswith(_CACHE_MAGIC):
            return
        ids = np.frombuffer(data[len(_CACHE_MAGIC) :], dtype="<u8")
        with self._mu:  # runs inside open(), before _open flips true
            for row_id in ids:
                n = self._row_count_locked(int(row_id))
                if n:
                    self.cache.bulk_add(int(row_id), n)
        self.cache.recalculate()

    def _save_cache(self) -> None:
        ids = np.asarray(self.cache.ids(), dtype="<u8")
        tmp = self.cache_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_CACHE_MAGIC)
            f.write(ids.tobytes())
        os.replace(tmp, self.cache_path)

    def recalculate_cache(self) -> None:
        """Force the rank cache's rankings current: drain deferred write
        bookkeeping, then rebuild (bypasses the 10s invalidate debounce —
        the fragment-level equivalent of cache.Recalculate)."""
        with self._mu:
            self._flush_row_bookkeeping()
            # Pending bulk-overlay rows aren't in the rank cache yet
            # (bulk_set_planes defers all derived bookkeeping): seed
            # them here with merged counts so a recalculated ranking
            # reflects read-your-writes without materializing roaring.
            for row_id in sorted(self._bulk_planes):
                self.cache.bulk_add(row_id, self._row_count_locked(row_id))
            self.cache.recalculate()

    def flush_cache(self) -> None:
        """Persist the rank cache sidecar (holder cache-flush loop target)."""
        with self._mu:
            self._flush_row_bookkeeping()
        self._save_cache()

    # -- positions ------------------------------------------------------

    def pos(self, row_id: int, column_id: int) -> int:
        """Linear bit position (fragment.go:1512-1514)."""
        return row_id * SLICE_WIDTH + (column_id % SLICE_WIDTH)

    # -- dirty-row journal (warm-state repair) ---------------------------

    def _log_dirty(self, rows) -> None:
        """Record one generation bump's touched rows (call with the lock
        held, AFTER self.generation was advanced).  ``rows`` None marks
        an unenumerable change (bulk import / restore): any delta
        spanning it forces a full rebuild downstream."""
        self._dirty_log.append(
            (self.generation, None if rows is None else tuple(rows))
        )
        if len(self._dirty_log) > _DIRTY_LOG_MAX:
            drop = len(self._dirty_log) - _DIRTY_LOG_MAX
            self._dirty_floor = self._dirty_log[drop - 1][0]
            del self._dirty_log[:drop]

    def rows_dirty_since(self, gen0: int) -> Optional[set]:
        """Rows written since generation ``gen0``, or None when the delta
        cannot be enumerated: the journal was evicted past gen0, a bulk
        import/restore landed in the span, or this fragment was created
        after gen0 (a recreated fragment's floor is its creation
        generation, so stale consumers of a deleted namesake always get
        None, never a partial delta)."""
        with self._mu:
            if gen0 == self.generation:
                return set()
            if gen0 < self._dirty_floor:
                return None
            out: set = set()
            for g, rows in reversed(self._dirty_log):
                if g <= gen0:
                    break
                if rows is None:
                    return None
                out.update(rows)
            return out

    # -- bit ops (fragment.go:371-459) ----------------------------------

    def set_bit(self, row_id: int, column_id: int) -> bool:
        with self._mu:
            self._assert_open()
            self._materialize_bulk_locked()
            changed = self.storage.add(self.pos(row_id, column_id))
            if changed:
                # Row bookkeeping (cache invalidation + rank-cache update)
                # is DEFERRED: the hot ingest loop only records the delta;
                # any reader that consults the caches flushes first
                # (_flush_row_bookkeeping).  Storage itself is always
                # current, and the write generation bumps eagerly so
                # engine-side matrices never serve stale hits.
                self.generation = next(_generation_counter)
                self._log_dirty((row_id,))
                p = self._pending_rows
                p[row_id] = p.get(row_id, 0) + 1
                self._increment_opn()
                self.stats.count("setN", 1)  # fragment.go:410
            return changed

    def set_bits(self, row_ids, column_ids) -> np.ndarray:
        """Durable batched SetBit: one vectorized storage pass + one WAL
        append for the whole batch (the host-side write batching of
        SURVEY §7 'hard parts (a)').

        Returns a bool array: per input position, whether that bit was
        newly set (duplicates within the batch count once, first wins —
        identical to issuing the SetBits sequentially).
        """
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        if len(row_ids) != len(column_ids):
            raise ValueError("row/column id length mismatch")
        positions = row_ids * np.uint64(SLICE_WIDTH) + (column_ids % np.uint64(SLICE_WIDTH))
        # Tiny batches (group-commit queue under light concurrency: mean
        # batch size is near the client count, often 1-8) skip the
        # vectorized machinery — np.unique/isin/split cost ~300 us of
        # numpy dispatch per call, vs a few us of scalar adds.  Same
        # semantics: one WAL append for the batch, first duplicate wins.
        if len(positions) <= 8:
            with self._mu:
                self._assert_open()
                self._materialize_bulk_locked()
                changed = np.zeros(len(positions), dtype=bool)
                added: list[int] = []
                for i, v in enumerate(positions.tolist()):
                    if self.storage.add_unlogged(v):
                        changed[i] = True
                        added.append(v)
                if added:
                    self.stats.count("setN", len(added))
                    self.generation = next(_generation_counter)
                    self._log_dirty({v // SLICE_WIDTH for v in added})
                    p = self._pending_rows
                    for v in added:
                        r = v // SLICE_WIDTH
                        p[r] = p.get(r, 0) + 1
                    self.storage.log_add_ops(np.asarray(added, dtype=np.uint64))
                    self._increment_opn()
                return changed
        with self._mu:
            self._assert_open()
            self._materialize_bulk_locked()
            # Apply first, then choose durability by how much was actually
            # new: a batch at/over the snapshot threshold goes straight to
            # snapshot (import_bits shape, the op records would be
            # superseded anyway); anything smaller appends its op records —
            # so mostly-duplicate batches cost a few WAL records, not a
            # fragment rewrite.
            added = self.storage.add_many_unlogged(positions)
            if len(added):
                self.stats.count("setN", len(added))
                self.generation = next(_generation_counter)
                rows_added, per_row = np.unique(
                    added // np.uint64(SLICE_WIDTH), return_counts=True
                )
                self._log_dirty(rows_added.tolist())
                p = self._pending_rows
                for row_id, cnt in zip(rows_added.tolist(), per_row.tolist()):
                    p[row_id] = p.get(row_id, 0) + cnt
                if len(added) >= self._effective_max_opn():
                    self._snapshot()
                else:
                    self.storage.log_add_ops(added)
                    self._increment_opn()
            # changed[i] = position newly added AND first occurrence in batch
            is_new = np.isin(positions, added)
            _, first_idx = np.unique(positions, return_index=True)
            first_mask = np.zeros(len(positions), dtype=bool)
            first_mask[first_idx] = True
            return is_new & first_mask

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        with self._mu:
            self._assert_open()
            self._materialize_bulk_locked()
            changed = self.storage.remove(self.pos(row_id, column_id))
            if changed:
                self.generation = next(_generation_counter)
                self._log_dirty((row_id,))
                p = self._pending_rows
                p[row_id] = p.get(row_id, 0) - 1
                self._increment_opn()
                self.stats.count("clearN", 1)  # fragment.go:456
            return changed

    def contains(self, row_id: int, column_id: int) -> bool:
        with self._mu:
            self._assert_open()
            pos = self.pos(row_id, column_id)
            if self.storage.contains(pos):
                return True
            # A bit may still be pending in the bulk overlay: point reads
            # merge it in word space (no materialization for a read).
            ov = self._bulk_planes.get(row_id)
            if ov is None:
                return False
            local = pos - row_id * SLICE_WIDTH
            return bool((int(ov[local >> 5]) >> (local & 31)) & 1)

    # -- native write request lane (write-side twin of pn_serve_pairs) ---

    def _writelane_state(self) -> Optional[dict]:
        """Build (or revalidate) the armed container table handed to
        ``pn_write_batch`` — call with the lock held.  The table covers
        every ARRAY container, each with a writable slack buffer
        (``_ensure_slack``), so the native crossing can memmove-insert
        in place; bitmap containers simply aren't in the table and ops
        touching them decline to the Python path.  Validity = storage
        identity (a snapshot re-attach swaps storage and strands the
        buffers) + write generation (any foreign writer may have
        restructured containers or reallocated a buffer)."""
        st = self._writelane
        storage = self.storage
        if (
            st is not None
            and st["storage"] is storage
            and st["gen"] == self.generation
        ):
            return st
        keys_l: list[int] = []
        objs: list = []
        addrs: list[int] = []
        ns_l: list[int] = []
        caps: list[int] = []
        bkeys_l: list[int] = []
        for key in sorted(storage.containers):
            c = storage.containers[key]
            arr = c.array
            if arr is None:
                # Bitmap container: not natively insertable — recorded in
                # the bkeys side table so the tree READ lane can tell
                # "bitmap here, decline" from "empty row segment".
                bkeys_l.append(key)
                continue
            n = len(arr)
            c._ensure_slack(n)
            keys_l.append(key)
            objs.append(c)
            addrs.append(c._buf_addr)
            ns_l.append(n)
            caps.append(len(c._buf))
        keys_a = np.array(keys_l, dtype=np.uint64)
        addrs_a = np.array(addrs, dtype=np.uint64)
        ns_a = np.array(ns_l, dtype=np.int64)
        caps_a = np.array(caps, dtype=np.int64)
        bkeys_a = np.array(bkeys_l, dtype=np.uint64)
        st = {
            "storage": storage,
            "gen": self.generation,
            "keys": keys_a,
            "addrs": addrs_a,
            "ns": ns_a,
            "caps": caps_a,
            "bkeys": bkeys_a,
            "objs": objs,
            # Raw base addresses, cached once per rebuild: .ctypes.data
            # costs ~1.4 us per access — 4 accesses per request would
            # dominate the singleton crossing.  In-place updates
            # (touch/apply) never move these buffers.
            "ptrs": (
                keys_a.ctypes.data, addrs_a.ctypes.data,
                ns_a.ctypes.data, caps_a.ctypes.data,
            ),
            "bptr": bkeys_a.ctypes.data,
            "n": len(keys_a),
            "n_bkeys": len(bkeys_a),
        }
        self._writelane = st
        return st

    def serve_tree(self, src: bytes, frame_b: bytes, allow_default: bool,
                   rowkey_b: bytes):
        """Fused nested-tree READ lane: parse an all-Count(op-tree over
        Bitmap leaves) body and evaluate it against this fragment's armed
        container table in one GIL-released ``pn_serve_tree`` crossing —
        the read-side use of the write lane's table.  Runs under the
        fragment lock for the whole call: native writers mutate those
        buffers in place, so the read must exclude them.

        Returns i64[N] counts, or None for any decline (native
        unavailable, non-canonical body, a leaf touching a bitmap
        container, containers born since the table was built) — the
        caller falls back to the general path.
        """
        with self._mu:
            self._assert_open()
            # The armed table reads container extents directly: pending
            # overlay planes would be invisible to it, so pay the debt.
            self._materialize_bulk_locked()
            st = self._writelane_state()
            if st is None or st.get("extra"):
                # Containers created through the scalar lane since the
                # build aren't in the table: a tree read would silently
                # see them as empty segments.
                return None
            kp, ap, np_, _cp = st["ptrs"]
            counts = native_mod.serve_tree(
                src, frame_b, allow_default, rowkey_b,
                kp, ap, np_, st["n"], st["bptr"], st["n_bkeys"],
            )
            if counts is not None:
                self.stats.count("servelane.tree_batches", 1)
            return counts

    def write_batch(self, src: bytes, frame_b: bytes, rowkey_b: bytes,
                    colkey_b: bytes):
        """One-crossing native write lane: parse a canonical
        all-SetBit/ClearBit request body, apply the sorted container
        inserts/removes, and group-commit the WAL records — all inside
        a single GIL-released ``pn_write_batch`` call against this
        fragment's armed container table.

        Returns:

        - ``(changed bool-array, types, rows, cols)`` — applied
          natively (WAL written, caches/journals/generation maintained
          here);
        - ``(None, types, rows, cols)`` — the body PARSED natively but
          a structural case (new/bitmap container, out-of-slice op, no
          slack) declined the apply; the caller pushes the parsed
          arrays through the Python batch path, still skipping the
          Python tokenizer;
        - ``None`` — full fallback (native unavailable, non-canonical
          body, buffered WAL writer): the caller runs the general lane.
        """
        W = np.uint64(SLICE_WIDTH)
        with self._mu:
            self._assert_open()
            self._materialize_bulk_locked()
            if self._writelane_cooldown > 0 and len(src) < 192:
                # SINGLETON structural declines dominated recently: the
                # per-op crossing is pure overhead on cold first-touch
                # streams — let the Python lanes serve for a stretch.
                # Batch bodies (a crossing amortized over many ops) are
                # never cooled down; 192 bytes ~ two canonical calls.
                self._writelane_cooldown -= 1
                return None
            storage = self.storage
            fd = -1 if storage.op_writer is None else storage._wal_fd()
            if fd == -2:
                return None  # buffered writer: C write(2) would reorder
            st = self._writelane_state()
            kp, ap, np_, cp = st["ptrs"]
            res = native_mod.write_batch(
                src, frame_b, rowkey_b, colkey_b,
                self.slice, SLICE_WIDTH,
                kp, ap, np_, cp, st["n"],
                fd, roaring.ARRAY_MAX_SIZE,
            )
            if res is None:
                return None
            types, rows, cols, changed = res
            native_apply = changed is not None
            if native_apply:
                self._writelane_streak = 0
            elif len(types) == 1:
                # Only singleton declines feed the cooldown: a batch's
                # scalar fallback already amortizes its crossing.
                self._writelane_streak += 1
                if self._writelane_streak >= 32:
                    self._writelane_streak = 0
                    self._writelane_cooldown = 512
            # Singleton scalar path: the n==1 request is THE hot shape;
            # numpy masking/unique/bincount machinery costs more than
            # the whole op there.
            if len(types) == 1:
                return self._write_batch_one(
                    st, storage, fd, native_apply, types, rows, cols, changed
                )
            if native_apply:
                self.stats.count("writelane.native_batches", 1)
                pos = rows * W + cols % W
            else:
                # Structural decline (new container, no slack, bitmap
                # container, clear-would-empty...).  An in-slice batch
                # of modest size still applies HERE through the scalar
                # storage lane (which creates containers and slack
                # buffers), with the armed table maintained
                # INCREMENTALLY — a full O(containers) rebuild per
                # first-touch op would be quadratic on uniform write
                # mixes.  Bigger or cross-slice batches hand the parse
                # back for the vectorized frame-level path.
                n = len(types)
                if n > 256 or not (cols // W == np.uint64(self.slice)).all():
                    self.stats.count("writelane.parsed_only", 1)
                    return None, types, rows, cols
                pos = rows * W + cols % W
                changed = np.zeros(n, dtype=bool)
                for i, (t, p_) in enumerate(zip(types.tolist(), pos.tolist())):
                    changed[i] = (
                        storage.add(p_) if t == 0 else storage.remove(p_)
                    )
                self.stats.count("writelane.scalar_batches", 1)
                # Refresh EVERY touched container (even unchanged ops
                # can reallocate slack buffers — see _write_batch_one).
                self._writelane_touch(
                    st, storage, np.unique(pos >> np.uint64(16))
                )
            n_changed = int(changed.sum())
            if n_changed:
                cpos = pos[changed]
                ctyp = types[changed]
                tkeys = np.unique(cpos >> np.uint64(16))
                if native_apply:
                    # Re-point the touched containers at their new
                    # extents (the crossing updated st["ns"] in place);
                    # op-log count and snapshot-mirror dirt are ours to
                    # record (the scalar lane did its own inside
                    # storage.add/remove).
                    for ti in st["keys"].searchsorted(tkeys).tolist():
                        c = st["objs"][ti]
                        c.array = c._buf[: int(st["ns"][ti])]
                        c._ser = None
                    if storage._snap_dirty is not None:
                        storage._snap_dirty.update(int(k) for k in tkeys.tolist())
                    if fd >= 0:
                        storage.op_n += n_changed
                n_set = int((ctyp == 0).sum())
                if n_set:
                    self.stats.count("setN", n_set)
                if n_changed - n_set:
                    self.stats.count("clearN", n_changed - n_set)
                # Same deferred bookkeeping as the scalar mutators: bump
                # the generation eagerly, journal the touched rows, and
                # leave rank/row-cache updates to the next reader.
                self.generation = next(_generation_counter)
                crow = (cpos // W).astype(np.int64)
                deltas = np.where(ctyp == 0, 1, -1)
                uro, inv = np.unique(crow, return_inverse=True)
                per_row = np.bincount(inv, weights=deltas).astype(np.int64)
                self._log_dirty(uro.tolist())
                p = self._pending_rows
                for r, dlt in zip(uro.tolist(), per_row.tolist()):
                    p[r] = p.get(r, 0) + int(dlt)
                if self._writelane is st:
                    st["gen"] = self.generation
                self._increment_opn()
                if self.storage is not storage:
                    # The opn trigger snapshotted and re-attached: the
                    # armed table points into the replaced containers.
                    self._writelane = None
            return changed, types, rows, cols

    def _write_batch_one(self, st, storage, fd, native_apply,
                         types, rows, cols, changed):
        """Singleton-request bookkeeping for write_batch (lock held):
        the exact work of set_bit/clear_bit, minus the numpy batch
        machinery the n==1 shape cannot amortize."""
        t0 = int(types[0])
        row0 = int(rows[0])
        col0 = int(cols[0])
        pos0 = row0 * SLICE_WIDTH + col0 % SLICE_WIDTH
        if native_apply:
            self.stats.count("writelane.native_batches", 1)
            ch = bool(changed[0])
        else:
            if col0 // SLICE_WIDTH != self.slice:
                self.stats.count("writelane.parsed_only", 1)
                return None, types, rows, cols
            ch = storage.add(pos0) if t0 == 0 else storage.remove(pos0)
            self.stats.count("writelane.scalar_batches", 1)
            changed = _CH_TRUE if ch else _CH_FALSE
            # Refresh even when unchanged: a duplicate add can still
            # reallocate the slack buffer (ensure-slack runs before the
            # duplicate check), which would strand a stale address in
            # the armed table.
            self._writelane_touch(st, storage, (pos0 >> 16,))
        if ch:
            key0 = pos0 >> 16
            if native_apply:
                ti = int(st["keys"].searchsorted(key0))
                c = st["objs"][ti]
                c.array = c._buf[: int(st["ns"][ti])]
                c._ser = None
                if storage._snap_dirty is not None:
                    storage._snap_dirty.add(key0)
                if fd >= 0:
                    storage.op_n += 1
            if t0 == 0:
                self.stats.count("setN", 1)
            else:
                self.stats.count("clearN", 1)
            self.generation = next(_generation_counter)
            self._log_dirty((row0,))
            p = self._pending_rows
            p[row0] = p.get(row0, 0) + (1 if t0 == 0 else -1)
            if self._writelane is st:
                st["gen"] = self.generation
            self._increment_opn()
            if self.storage is not storage:
                self._writelane = None
        return changed, types, rows, cols

    def _writelane_touch(self, st: dict, storage, tkeys) -> None:
        """Incrementally reconcile the armed table after a scalar-lane
        apply touched ``tkeys`` (call with the lock held).  Containers
        already in the table get their (addr, n, cap) refreshed (the
        scalar add may have reallocated the slack buffer); NEW
        containers accumulate in a side set served by the scalar lane
        until a bounded rebuild folds them in; a table entry whose
        container vanished (emptied by a clear) or densified to bitmap
        invalidates the state — the native crossing must never see a
        stale buffer address."""
        dead = False
        extra = st.setdefault("extra", set())
        keys = st["keys"]
        nkeys = len(keys)
        if isinstance(tkeys, np.ndarray):
            tkeys = tkeys.tolist()
        for k in tkeys:
            c = storage.containers.get(k)
            ti = int(keys.searchsorted(k))
            in_tab = ti < nkeys and int(keys[ti]) == k
            if c is None or c.array is None:
                if in_tab:
                    dead = True
                    break
                extra.discard(k)
                continue
            if in_tab:
                c._ensure_slack(len(c.array))
                st["addrs"][ti] = c._buf_addr
                st["ns"][ti] = len(c.array)
                st["caps"][ti] = len(c._buf)
                st["objs"][ti] = c
            else:
                extra.add(k)
        if dead or len(extra) > max(64, nkeys // 4):
            self._writelane = None

    def _flush_row_bookkeeping(self) -> None:
        """Apply deferred per-row cache invalidations + rank updates.

        Called (with the lock held) by every reader that consults the
        row/device/checksum/count caches or the rank cache; the ingest
        hot path only records (row, delta) so a burst of writes pays the
        bookkeeping once per touched row, not once per op.  Storage is
        never deferred — only derived caches are.
        """
        if not self._pending_rows:
            return
        pending = self._pending_rows
        self._pending_rows = {}
        for row_id, delta in pending.items():
            self._row_cache.pop(row_id, None)
            dropped = self._row_dev_cache.pop(row_id, None)
            if dropped is not None:
                # analysis-ok: check-then-act: every caller holds fragment._mu (locked-suffix convention; the rule sees only function-local locks)
                self._row_dev_cache_arrays -= len(dropped)
            self._checksums.pop(row_id // HASH_BLOCK_SIZE, None)
            # analysis-ok: check-then-act: every caller holds fragment._mu (locked-suffix convention; the rule sees only function-local locks)
            cached = self._row_counts.get(row_id)
            if cached is not None:
                rc = cached + delta
                self._row_counts[row_id] = rc
                self._row_counts.move_to_end(row_id)
            else:
                # Counts from storage AFTER the ops applied — the delta is
                # already included, so no adjustment here.
                rc = self._row_count_locked(row_id)
            self.cache.add(row_id, rc)

    def _increment_opn(self) -> None:
        # One comparison on the hot path: the full trigger computation
        # (env cache + container count scaling) runs only when op_n
        # crosses the cached value.  The cache may lag the true trigger
        # (container churn between crossings); the recompute at crossing
        # time makes the final snapshot decision, so the deviation is
        # only WHEN the check happens, never whether.
        if self.storage.op_n < self._opn_trigger:
            return
        t = self._effective_max_opn()
        if self.storage.op_n >= t:
            self.snapshot()
            t = self._effective_max_opn()
        self._opn_trigger = t

    def _effective_max_opn(self) -> int:
        """Snapshot trigger, scaled with fragment size for DEFAULT-tuned
        fragments.

        The reference's fixed MaxOpN=2000 (fragment.go:63-65) is sized
        for its ~ms C snapshot; here a snapshot serializes+reparses every
        container in Python/C++ (~7 us/container measured), so at a few
        thousand containers the fixed trigger makes snapshot amortization
        THE singleton-write cost (~58 us/op at 16k containers).  Scaling
        the trigger with container count keeps snapshot work a bounded
        fraction of write work, and crash recovery stays bounded: WAL
        replay runs at ~100k ops/s (native decode), so the 200k-op cap
        bounds re-open at ~2 s.  Only applies when max_opn is the
        default — an explicitly configured max_opn is honored exactly
        (reference-identical file-state behavior); set
        PILOSA_TPU_MAX_OPN_SCALE=0 to disable scaling entirely.
        """
        if self.max_opn != DEFAULT_MAX_OPN:
            return self.max_opn
        scale = self._max_opn_scale
        if scale is None:  # read once per fragment (env reads cost ~10us/op)
            scale = self._max_opn_scale = int(
                # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
                os.environ.get("PILOSA_TPU_MAX_OPN_SCALE", "8")
            )
        if scale <= 0:
            return self.max_opn
        return max(
            self.max_opn, min(len(self.storage.containers) * scale, 200_000)
        )

    # -- snapshotting (fragment.go:1017-1057) ---------------------------

    def snapshot(self) -> None:
        """Rewrite the data file from storage; temp-file + rename."""
        with self._mu:
            self._assert_open()
            # The snapshot file is the restore-path truth: fold any
            # pending bulk overlay in first so no bits live only in RAM.
            self._materialize_bulk_locked()
            self._snapshot()

    def _snapshot(self) -> None:
        import time as _time

        t0 = _time.perf_counter()
        dirname = os.path.dirname(self.path) or "."
        # The "<name>." prefix + suffix pair makes the orphan-sweep glob in
        # open() precise: fragment "0" must not match fragment "01"'s temps.
        fd, tmp = tempfile.mkstemp(
            prefix=os.path.basename(self.path) + ".", suffix=".snapshotting", dir=dirname
        )
        try:
            with os.fdopen(fd, "wb") as f:
                self.storage.write_to(f)
            os.replace(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        self.storage.op_n = 0
        # Re-attach zero-copy to the NEW snapshot file (the reference
        # re-mmaps after every snapshot, fragment.go:1017-1057): the
        # re-parsed storage is byte-equivalent to the in-memory state just
        # written, heap containers become file views again, and the old
        # mapping (pinning the replaced inode) is released.  Readers
        # holding the old bitmap keep their immutable snapshot.  Costs one
        # O(containers) parse on top of the O(containers) write this
        # method just did; skipped when mmap is disabled.
        old_mm = self._storage_map
        data, mm = self._map_storage() if self._mmap_enabled() else (None, None)
        if mm is not None:
            self.storage = roaring.Bitmap.from_bytes(data, zero_copy=True)
            self._storage_map = mm
            if old_mm is not None:
                try:
                    old_mm.close()
                except BufferError:
                    pass  # a reader still views it; GC finishes later
        self._attach_wal()
        # duration logging analog (fragment.go:1012-1020); timing() takes
        # seconds (sinks convert to ms themselves).
        self.stats.timing("snapshot", _time.perf_counter() - t0)

    # -- row reads (fragment.go:332-367) --------------------------------

    def _assert_open(self) -> None:
        """Guard for read paths: close() swaps storage to an empty bitmap
        (to release the mmap), so a late reader must fail loudly instead
        of silently observing an empty fragment."""
        if not self._open:
            raise ErrFragmentClosed(f"fragment closed: {self.path}")

    def row_dense(self, row_id: int) -> np.ndarray:
        """One row of this slice as packed uint32 words (device layout)."""
        with self._mu:
            self._assert_open()
            self._flush_row_bookkeeping()
            cached = self._row_cache.get(row_id)
            if cached is not None:
                self._row_cache.move_to_end(row_id)
                return cached
            words = self.storage.to_dense_words(row_id * SLICE_WIDTH, SLICE_WIDTH)
            ov = self._bulk_planes.get(row_id)
            if ov is not None:
                # Pending bulk overlay: the dense read merges it for free
                # (one word-wise OR) — this is why bulk commits serve
                # read-your-writes without touching roaring containers.
                words = words | ov
            self._row_cache[row_id] = words
            while len(self._row_cache) > self._row_cache_max:
                self._row_cache.popitem(last=False)
            return words

    def row_device(self, row_id: int, engine):
        """Dense row as an ENGINE array, cached device-side.

        On the jax engine the packed words stay resident in HBM across
        queries (the fragment's device working set); repeat reads of hot
        rows cost zero host→device traffic.  Mutations invalidate the row
        (see _on_row_mutated), so reads are always current.
        """
        # Compute-and-insert stays under one lock hold: inserting after a
        # release could overwrite the invalidation of a concurrent mutation
        # with a stale row.
        ename = getattr(engine, "name", "?")
        with self._mu:
            self._flush_row_bookkeeping()
            per_row = self._row_dev_cache.get(row_id)
            if per_row is not None:
                cached = per_row.get(ename)
                if cached is not None:
                    self._row_dev_cache.move_to_end(row_id)
                    return cached
            arr = engine.asarray(self.row_dense(row_id))
            if per_row is None:
                per_row = self._row_dev_cache[row_id] = {}
            per_row[ename] = arr
            self._row_dev_cache_arrays += 1
            self._row_dev_cache.move_to_end(row_id)
            while self._row_dev_cache_arrays > self._row_dev_cache_max:
                _, evicted = self._row_dev_cache.popitem(last=False)
                self._row_dev_cache_arrays -= len(evicted)
            return arr

    def row(self, row_id: int) -> roaring.Bitmap:
        """Row as a roaring bitmap of global column positions for this slice."""
        with self._mu:
            self._assert_open()
            # Roaring-shaped read: container structure is observed, so any
            # pending overlay must be in storage first.
            self._materialize_bulk_locked()
            return self.storage.offset_range(
                self.slice * SLICE_WIDTH, row_id * SLICE_WIDTH, (row_id + 1) * SLICE_WIDTH
            )

    def row_count(self, row_id: int) -> int:
        with self._mu:
            self._assert_open()
            self._flush_row_bookkeeping()
            return self._row_count_locked(row_id)

    def _row_count_locked(self, row_id: int) -> int:
        """Cached row cardinality; sole owner of the count+store logic."""
        # analysis-ok: check-then-act: every caller holds fragment._mu (locked-suffix convention; the rule sees only function-local locks)
        rc = self._row_counts.get(row_id)
        if rc is None:
            ov = self._bulk_planes.get(row_id)
            if ov is None:
                rc = self.storage.count_range(
                    row_id * SLICE_WIDTH, (row_id + 1) * SLICE_WIDTH
                )
            elif self.storage.count_range(
                row_id * SLICE_WIDTH, (row_id + 1) * SLICE_WIDTH
            ) == 0:
                # Bulk-into-empty row (the common build shape): the
                # overlay IS the row; no dense expansion needed.
                rc = bw.count_words(ov)
            else:
                # Overlay rows count over the merged dense view (overlap
                # with storage bits makes count_range + popcount(ov) wrong).
                words = self.storage.to_dense_words(
                    row_id * SLICE_WIDTH, SLICE_WIDTH
                )
                rc = bw.count_words(words | ov)
            self._row_counts[row_id] = rc
            while len(self._row_counts) > self._row_counts_max:
                self._row_counts.popitem(last=False)
        else:
            self._row_counts.move_to_end(row_id)
        return rc

    def max_row(self) -> int:
        with self._mu:
            m = self.storage.max() // SLICE_WIDTH
            if self._bulk_planes:
                m = max(m, max(self._bulk_planes))
            return m

    def count(self) -> int:
        with self._mu:
            self._assert_open()
            # Whole-fragment cardinality needs the deduplicated union;
            # cheapest exact answer is to pay the overlay debt.
            self._materialize_bulk_locked()
            return self.storage.count()

    # -- TopN (fragment.go:493-659) -------------------------------------

    def top_pairs(self, row_ids: Sequence[int]) -> list[cache_mod.Pair]:
        """Candidate (id, count) pairs, count-descending (topBitmapPairs)."""
        with self._mu:
            self._flush_row_bookkeeping()
        if not row_ids:
            self.cache.invalidate()
            return list(self.cache.top())
        pairs = []
        for row_id in row_ids:
            n = self.cache.get(row_id) or self.row_count(row_id)
            if n > 0:
                pairs.append(cache_mod.Pair(id=row_id, count=n))
        return cache_mod.pairs_sorted(pairs)

    def top(self, opt: TopOptions) -> list[cache_mod.Pair]:
        pairs = self.top_pairs(list(opt.row_ids))
        n = 0 if opt.row_ids else opt.n  # explicit ids -> no truncation

        filters = set(opt.filter_values) if (opt.filter_field and opt.filter_values) else None

        tanimoto = opt.tanimoto_threshold if (opt.tanimoto_threshold > 0 and opt.has_src) else 0
        src_count = 0
        if tanimoto:
            src_count = (
                opt.src.count()
                if opt.src is not None
                else int(bw.np_popcount(opt.src_dense).sum())
            )
        min_tan = (src_count * tanimoto) / 100.0 if tanimoto else 0.0
        max_tan = (src_count * 100.0) / tanimoto if tanimoto else 0.0

        # Pre-filter candidates on cached counts (cheap, host-side).
        cands: list[cache_mod.Pair] = []
        for p in pairs:
            if p.count <= 0:
                continue
            if tanimoto:
                if p.count <= min_tan or p.count >= max_tan:
                    continue
            elif p.count < opt.min_threshold:
                continue
            if filters is not None:
                attrs = self.row_attr_store.attrs(p.id) if self.row_attr_store else None
                if not attrs or attrs.get(opt.filter_field) not in filters:
                    continue
            cands.append(p)

        if not opt.has_src:
            # Counts are final; take the first n.
            results = cands[:n] if n else cands
            return cache_mod.pairs_sorted(results)

        # Intersection-count phase: process candidates count-descending in
        # chunks; batched popcount per chunk; heap-threshold pruning between
        # candidates exactly as the reference does between iterations.
        src_dense = (
            opt.src_dense
            if opt.src_dense is not None
            else opt.src.to_dense_words(self.slice * SLICE_WIDTH, SLICE_WIDTH)
        )
        results: list[cache_mod.Pair] = []
        chunk = TOPN_SCORE_CHUNK
        i = 0
        while i < len(cands):
            batch = cands[i : i + chunk]
            i += chunk
            counts = None
            if opt.scorer is not None:
                counts = opt.scorer([p.id for p in batch])
            if counts is None:  # no scorer, or scorer declined this chunk
                rows = np.stack([self.row_dense(p.id) for p in batch])
                counts = _batch_intersection_counts(rows, src_dense)
            else:
                counts = np.asarray(counts)
            stop = False
            for p, count in zip(batch, counts.tolist()):
                if n and len(results) >= n:
                    results.sort(key=lambda q: q.count)
                    threshold = results[0].count
                    if threshold < opt.min_threshold or p.count < threshold:
                        stop = True
                        break
                    if count < threshold:
                        continue
                    results.pop(0)
                    results.append(cache_mod.Pair(id=p.id, count=count))
                    continue
                if count == 0:
                    continue
                if tanimoto:
                    t = math.ceil(count * 100.0 / (p.count + src_count - count))
                    if t <= tanimoto:
                        continue
                elif count < opt.min_threshold:
                    continue
                results.append(cache_mod.Pair(id=p.id, count=count))
            if stop:
                break
        return cache_mod.pairs_sorted(results)

    # -- bulk import (fragment.go:924-989) ------------------------------

    def import_bits(self, row_ids: Sequence[int], column_ids: Sequence[int]) -> None:
        """Bulk load; WAL detached, one snapshot at the end."""
        with self._mu:
            self._assert_open()
            self._materialize_bulk_locked()
            self._import_bits(row_ids, column_ids)

    def _import_bits(self, row_ids, column_ids) -> None:
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        if len(row_ids) != len(column_ids):
            raise ValueError("row/column id length mismatch")
        positions = row_ids * np.uint64(SLICE_WIDTH) + (column_ids % np.uint64(SLICE_WIDTH))
        self.storage.op_writer = None  # detach WAL during bulk load
        try:
            self.storage.add_many(positions)
        finally:
            self.storage.op_writer = self._wal
        self.generation = next(_generation_counter)
        self._log_dirty(None)  # bulk load: delta unenumerable by design
        self._row_cache.clear()
        self._row_dev_cache.clear()
        self._row_dev_cache_arrays = 0
        self._checksums.clear()
        self._row_counts.clear()
        for row_id in np.unique(row_ids):
            self.cache.bulk_add(int(row_id), self.row_count(int(row_id)))
        self.cache.recalculate()
        self.snapshot()

    # -- device bulk build commit (pilosa_tpu/bulk) ----------------------

    def bulk_set_planes(self, row_ids, planes) -> int:
        """Commit packed word planes from the device bulk builder as a
        PENDING dense overlay — no roaring conversion here (that is the
        lazy half; see bulk/lazy.py and _materialize_bulk_locked).

        ``planes[i]`` is a uint32[SLICE_WIDTH/32] plane of bits to OR
        into row ``row_ids[i]``.  Serving reads (row_dense, contains,
        row counts, TopN scoring) merge the overlay immediately, so
        read-your-writes holds from the moment this returns; any
        roaring-shaped touch materializes first.  Returns the number of
        planes committed.
        """
        planes = np.asarray(planes, dtype=np.uint32)
        if planes.ndim != 2 or planes.shape[1] != _WORDS:
            raise ValueError("planes must be (G, SLICE_WIDTH/32) uint32")
        if len(row_ids) != len(planes):
            raise ValueError("row/plane length mismatch")
        with self._mu:
            self._assert_open()
            if len(planes) == 0:
                return 0
            was_empty = not self._bulk_planes
            ov = self._bulk_planes
            rows = [int(r) for r in row_ids]
            for row_id, plane in zip(rows, planes):
                cur = ov.get(row_id)
                if cur is None:
                    ov[row_id] = plane.copy()
                else:
                    np.bitwise_or(cur, plane, out=cur)
                self._bulk_drop_row_caches_locked(row_id)
            self._bulk_commit_tail_locked(rows, was_empty)
            return len(rows)

    def bulk_or_words(self, row_ids, counts, word_idx, word_vals) -> int:
        """Sparse twin of :meth:`bulk_set_planes`: OR individual plane
        words into the overlay from the builder's CSR form
        (``counts[i]`` words for ``row_ids[i]``; ``word_idx`` in-plane
        word indices, UNIQUE within each group — the builder's segment
        stage guarantees it, and the fancy-indexed OR below silently
        drops duplicates; ``word_vals`` their uint32 values).

        A chunk's pairs touch a few hundred words per plane, so this
        avoids materializing and merging full 32768-word planes per
        chunk — each overlay plane is allocated once and only its
        touched words are written.  Semantics are identical to
        committing the equivalent dense planes."""
        counts = np.asarray(counts, dtype=np.int64)
        word_idx = np.asarray(word_idx, dtype=np.int64)
        word_vals = np.asarray(word_vals, dtype=np.uint32)
        if len(row_ids) != len(counts):
            raise ValueError("row/count length mismatch")
        if len(word_idx) != len(word_vals) or int(counts.sum()) != len(word_idx):
            raise ValueError("word CSR length mismatch")
        if len(word_idx) and (
            int(word_idx.min()) < 0 or int(word_idx.max()) >= _WORDS
        ):
            raise ValueError("word index out of plane range")
        offs = np.concatenate([np.zeros(1, dtype=np.int64), np.cumsum(counts)])
        with self._mu:
            self._assert_open()
            if len(counts) == 0:
                return 0
            was_empty = not self._bulk_planes
            ov = self._bulk_planes
            rows = [int(r) for r in row_ids]
            for i, row_id in enumerate(rows):
                cur = ov.get(row_id)
                if cur is None:
                    cur = ov[row_id] = np.zeros(_WORDS, dtype=np.uint32)
                lo, hi = offs[i], offs[i + 1]
                cur[word_idx[lo:hi]] |= word_vals[lo:hi]
                self._bulk_drop_row_caches_locked(row_id)
            self._bulk_commit_tail_locked(rows, was_empty)
            return len(rows)

    def _bulk_drop_row_caches_locked(self, row_id: int) -> None:
        """An overlay commit changes the row by an UNKNOWN delta (the
        committed bits may overlap existing ones), which the deferred
        (row -> delta) bookkeeping cannot express — drop the derived
        caches for the row outright instead."""
        self._row_cache.pop(row_id, None)
        dropped = self._row_dev_cache.pop(row_id, None)
        if dropped is not None:
            # analysis-ok: check-then-act: every caller holds fragment._mu (locked-suffix convention; the rule sees only function-local locks)
            self._row_dev_cache_arrays -= len(dropped)
        self._checksums.pop(row_id // HASH_BLOCK_SIZE, None)
        self._row_counts.pop(row_id, None)

    def _bulk_commit_tail_locked(self, rows, was_empty: bool) -> None:
        """Shared overlay-commit bookkeeping: eager generation bump
        (armed write-lane tables, engine row matrices, and qcache
        vectors keyed on the old generation must not serve pre-overlay
        state), dirty-row journal, stats, and the lazy ledger's pending
        note on the empty -> non-empty transition."""
        self.generation = next(_generation_counter)
        self._log_dirty(rows)
        self.stats.count("bulk.commit_rows", len(rows))
        if was_empty:
            from pilosa_tpu.bulk.lazy import LEDGER

            LEDGER.note_pending(self)

    def materialize_bulk(self) -> int:
        """Convert any pending bulk overlay into roaring storage (the
        materialization ledger's drain entry point).  Returns the number
        of overlay rows folded in; 0 on a closed fragment (close()
        already paid the debt)."""
        with self._mu:
            if not self._open:
                return 0
            return self._materialize_bulk_locked()

    def _materialize_bulk_locked(self) -> int:
        """Pay the overlay debt: fold every pending plane into roaring
        storage, WAL-or-snapshot durable, generation bumped (the
        conversion restructures containers, so armed write-lane tables
        and zero-copy readers must revalidate).  Call with the lock
        held.  Reentrancy-safe: the overlay detaches first, so the
        snapshot trigger's re-entry through snapshot() sees no debt.
        A no-op (one dict truthiness check) when there is no overlay —
        every guarded touch path calls this unconditionally."""
        ov = self._bulk_planes
        if not ov:
            return 0
        import time as _time

        from pilosa_tpu.bulk.build import plane_positions
        from pilosa_tpu.bulk.lazy import LEDGER

        t0 = _time.perf_counter()
        self._bulk_planes = {}
        rows = sorted(ov)
        positions = np.concatenate(
            [plane_positions(ov[r], base=r * SLICE_WIDTH) for r in rows]
        )
        added = self.storage.add_many_unlogged(positions)
        if len(added):
            self.generation = next(_generation_counter)
            self._log_dirty(rows)
            if len(added) >= self._effective_max_opn():
                self._snapshot()
            else:
                self.storage.log_add_ops(added)
                self._increment_opn()
        # Row-level derived caches stay: the fragment's LOGICAL content
        # is unchanged by materialization (reads merged the overlay all
        # along) — only the container structure moved.
        self.stats.count("bulk.materialized_rows", len(rows))
        self.stats.timing("bulk.materialize", _time.perf_counter() - t0)
        LEDGER.note_materialized(self)
        return len(rows)

    def export_pairs(self) -> tuple[np.ndarray, np.ndarray]:
        """All set bits as global (row_ids, col_ids) uint64 columns in
        ascending position order — the columnar egress source.  Merges
        any pending bulk overlay in position space WITHOUT materializing
        roaring containers: egress is a dense read, and staying lazy
        here is the point of the columnar door."""
        with self._mu:
            self._assert_open()
            positions = np.asarray(self.storage.to_array(), dtype=np.uint64)
            if self._bulk_planes:
                from pilosa_tpu.bulk.build import plane_positions

                extra = np.concatenate(
                    [
                        plane_positions(plane, base=r * SLICE_WIDTH)
                        for r, plane in sorted(self._bulk_planes.items())
                    ]
                )
                positions = np.union1d(positions, extra)
        rows = positions // np.uint64(SLICE_WIDTH)
        cols = positions % np.uint64(SLICE_WIDTH) + np.uint64(
            self.slice * SLICE_WIDTH
        )
        return rows, cols

    # -- block checksums & merge (fragment.go:681-920) -------------------

    def checksum(self) -> bytes:
        """Checksum of the whole fragment: hash of (block id, block
        checksum) pairs in block order.

        POSITION-BOUND: the block id participates in the hash, so two
        fragments whose blocks hold the same relative bit pattern at
        DIFFERENT block ids cannot collide (block checksums are
        relative to their block's base row by construction).  The
        digest is a pure function of the logical bit set — identical
        bits reached through any write order, the patch or rebuild
        path, or a write_to/read_from round trip hash identically —
        which is the property the replica digest protocol
        (replica/digest.py) and anti-entropy repair rest on.

        Cached per write generation: digest sweeps over an idle holder
        re-hash nothing (every mutator bumps ``generation``, which
        invalidates the cache by key, never by callback)."""
        with self._mu:
            self._assert_open()
            # Digests hash storage positions: a pending overlay must be
            # folded in or replicas would disagree on identical content.
            self._materialize_bulk_locked()
            self._flush_row_bookkeeping()
            gen = self.generation
            cached = self._checksum_cache
            if cached is not None and cached[0] == gen:
                return cached[1]
            h = hashlib.sha1()
            for block_id, chk in self._blocks():
                h.update(block_id.to_bytes(8, "little"))
                h.update(chk)
            digest = h.digest()
            self._checksum_cache = (gen, digest)
            return digest

    def blocks(self) -> list[tuple[int, bytes]]:
        """(block id, sha1) for each non-empty block of HASH_BLOCK_SIZE rows."""
        with self._mu:
            self._assert_open()
            self._materialize_bulk_locked()
            self._flush_row_bookkeeping()
            return self._blocks()

    def _blocks(self) -> list[tuple[int, bytes]]:
        positions = self.storage.to_array()
        if len(positions) == 0:
            return []
        block_ids = (positions // np.uint64(SLICE_WIDTH * HASH_BLOCK_SIZE)).astype(np.int64)
        out = []
        for bid in np.unique(block_ids):
            bid = int(bid)
            # analysis-ok: check-then-act: _blocks runs only under fragment._mu (checksum() takes it; the rule sees only function-local locks)
            chk = self._checksums.get(bid)
            if chk is None:
                block = positions[block_ids == bid]
                rel = block - np.uint64(bid * SLICE_WIDTH * HASH_BLOCK_SIZE)
                chk = hashlib.sha1(rel.astype("<u8").tobytes()).digest()
                self._checksums[bid] = chk
            out.append((bid, chk))
        return out

    def block_data(self, block_id: int) -> tuple[np.ndarray, np.ndarray]:
        """(row_ids, column_ids) of all bits in a block (fragment.go:785-794)."""
        start = block_id * HASH_BLOCK_SIZE * SLICE_WIDTH
        end = (block_id + 1) * HASH_BLOCK_SIZE * SLICE_WIDTH
        with self._mu:
            self._assert_open()
            self._materialize_bulk_locked()
            positions = self.storage.slice_values(start, end)
        rows = positions // np.uint64(SLICE_WIDTH)
        cols = positions % np.uint64(SLICE_WIDTH)
        return rows, cols

    def merge_block(
        self, block_id: int, pair_sets: list[tuple[np.ndarray, np.ndarray]]
    ) -> list[tuple[tuple[np.ndarray, np.ndarray], tuple[np.ndarray, np.ndarray]]]:
        """Majority-vote block merge (fragment.go:802-920).

        ``pair_sets[i]`` is node i's (row_ids, column_ids) for this block;
        pair_sets[0] must be the local node.  A bit is canonical when set on
        >= (len(pair_sets)+1)//2 nodes.  Returns, per node, the diff
        ((set_rows, set_cols), (clear_rows, clear_cols)) to converge, and
        applies the local node's diff to storage.
        """
        m = len(pair_sets)
        majority = (m + 1) // 2
        pos_sets = []
        for rows, cols in pair_sets:
            rows = np.asarray(rows, dtype=np.uint64)
            cols = np.asarray(cols, dtype=np.uint64)
            pos_sets.append(rows * np.uint64(SLICE_WIDTH) + cols)
        all_pos = np.concatenate(pos_sets) if pos_sets else np.empty(0, np.uint64)
        uniq, counts = np.unique(all_pos, return_counts=True)
        target = uniq[counts >= majority]

        diffs = []
        for pos in pos_sets:
            sets = np.setdiff1d(target, pos)
            clears = np.setdiff1d(pos, target)
            diffs.append(
                (
                    (sets // np.uint64(SLICE_WIDTH), sets % np.uint64(SLICE_WIDTH)),
                    (clears // np.uint64(SLICE_WIDTH), clears % np.uint64(SLICE_WIDTH)),
                )
            )

        # Apply local diff (node 0) through the normal mutation path.
        (set_rows, set_cols), (clear_rows, clear_cols) = diffs[0]
        for r, c in zip(set_rows.tolist(), set_cols.tolist()):
            self.set_bit(int(r), int(c))
        for r, c in zip(clear_rows.tolist(), clear_cols.tolist()):
            self.clear_bit(int(r), int(c))
        return diffs

    # -- backup payload (fragment.go:1096-1266) --------------------------

    def write_to(self, w) -> int:
        """Serialize current storage (snapshot format, no pending ops)."""
        with self._mu:
            if self._open:
                # Backup/resync payloads must carry the overlay bits; a
                # closed fragment already materialized during close().
                self._materialize_bulk_locked()
            return self.storage.write_to(w)

    def read_from(self, data: bytes) -> None:
        """Replace contents from a snapshot byte string (restore path)."""
        with self._mu:
            self._read_from(data)

    def _read_from(self, data: bytes) -> None:
        if self._bulk_planes:
            # Wholesale restore supersedes the pending overlay: the
            # incoming snapshot IS the new truth, debt and all.
            self._bulk_planes = {}
            from pilosa_tpu.bulk.lazy import LEDGER

            LEDGER.note_materialized(self)
        self.storage = roaring.Bitmap.from_bytes(data)
        self.storage.op_n = 0
        self.generation = next(_generation_counter)
        self._log_dirty(None)  # wholesale restore: delta unenumerable
        self._row_cache.clear()
        self._row_dev_cache.clear()
        self._row_dev_cache_arrays = 0
        self._checksums.clear()
        self._row_counts.clear()
        self.snapshot()
        self._rebuild_cache()

    def _rebuild_cache(self) -> None:
        self.cache = cache_mod.new_cache(
            self.cache_type, self.cache_size, self.ranking_debounce_s
        )
        positions = self.storage.to_array()
        if len(positions):
            rows, counts = np.unique(positions // np.uint64(SLICE_WIDTH), return_counts=True)
            for r, c in zip(rows.tolist(), counts.tolist()):
                self.cache.bulk_add(int(r), int(c))
        self.cache.recalculate()
