"""Frame: a named matrix of rows × columns with views and row attributes.

Reference analog: frame.go.  A frame owns its views (standard, optional
inverse, time-quantum sub-views), a row AttrStore, and per-frame options
(rowLabel, cacheType/cacheSize, inverseEnabled, timeQuantum) persisted in a
``.meta`` sidecar (frame.go:281-336; JSON here rather than protobuf — the
on-disk meta is node-internal, only the HTTP wire format is
reference-compatible).

SetBit fans out to the standard view plus one view per time-quantum unit
(frame.go:446-485); the inverse view stores the transposed bit
(columnID, rowID) so column-axis queries are row reads (frame.go:530-606).
"""

from __future__ import annotations

import json
import os
import threading

from pilosa_tpu.analysis import lockcheck
from datetime import datetime
from typing import Optional, Sequence

import numpy as np

from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core import timequantum as tq
from pilosa_tpu.core.attr import AttrStore
from pilosa_tpu.core.fragment import DEFAULT_CACHE_SIZE
from pilosa_tpu.core.view import VIEW_INVERSE, VIEW_STANDARD, View, is_inverse_view, is_valid_view
from pilosa_tpu.pilosa import (
    ErrFrameInverseDisabled,
    ErrInvalidView,
    SLICE_WIDTH,
    validate_label,
    validate_name,
)

DEFAULT_ROW_LABEL = "rowID"
DEFAULT_CACHE_TYPE = cache_mod.DEFAULT_CACHE_TYPE


class FrameOptions:
    def __init__(
        self,
        row_label: str = "",
        inverse_enabled: bool = False,
        cache_type: str = "",
        cache_size: int = 0,
        time_quantum: str = "",
    ):
        self.row_label = row_label
        self.inverse_enabled = inverse_enabled
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.time_quantum = time_quantum

    def validate(self) -> None:
        """Raise for any invalid option — callers check BEFORE creating
        frame state on disk, so a rejected create leaves no ghost frame."""
        if self.row_label:
            validate_label(self.row_label)
        if self.cache_type:
            cache_mod.new_cache(self.cache_type, 1)
        if self.time_quantum:
            tq.parse_time_quantum(self.time_quantum)

    def to_json(self) -> dict:
        return {
            "rowLabel": self.row_label,
            "inverseEnabled": self.inverse_enabled,
            "cacheType": self.cache_type,
            "cacheSize": self.cache_size,
            "timeQuantum": self.time_quantum,
        }


class Frame:
    def __init__(
        self,
        path: str,
        index: str,
        name: str,
        stats=None,
        on_new_fragment=None,
        ranking_debounce_s=None,
    ):
        from pilosa_tpu.stats import NOP_STATS

        validate_name(name)
        self.path = path
        self.index = index
        self.name = name
        self.stats = stats if stats is not None else NOP_STATS
        self.on_new_fragment = on_new_fragment
        self.ranking_debounce_s = ranking_debounce_s

        self.row_label = DEFAULT_ROW_LABEL
        self.inverse_enabled = False
        self.cache_type = DEFAULT_CACHE_TYPE
        self.cache_size = DEFAULT_CACHE_SIZE
        self.time_quantum = ""

        # Guards view create against concurrent writers (frame.go mu analog).
        self._mu = lockcheck.named_rlock("core.frame._mu")
        self.views: dict[str, View] = {}
        self.row_attr_store = AttrStore(os.path.join(path, "row_attrs.db"))

    # -- lifecycle ------------------------------------------------------

    def open(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        self._load_meta()
        self.row_attr_store.open()
        views_dir = os.path.join(self.path, "views")
        os.makedirs(views_dir, exist_ok=True)
        for entry in sorted(os.listdir(views_dir)):
            if entry.startswith("."):
                continue
            self._open_view(entry)

    def close(self) -> None:
        self.row_attr_store.close()
        for v in list(self.views.values()):
            v.close()
        self.views.clear()

    def flush_caches(self) -> None:
        # list() snapshots: schema merges may insert concurrently
        for v in list(self.views.values()):
            v.flush_caches()

    @property
    def meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def _load_meta(self) -> None:
        try:
            with open(self.meta_path) as f:
                meta = json.load(f)
        except FileNotFoundError:
            return
        self.row_label = meta.get("rowLabel", DEFAULT_ROW_LABEL)
        self.inverse_enabled = meta.get("inverseEnabled", False)
        self.cache_type = meta.get("cacheType", DEFAULT_CACHE_TYPE)
        self.cache_size = meta.get("cacheSize", DEFAULT_CACHE_SIZE)
        self.time_quantum = meta.get("timeQuantum", "")

    def save_meta(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        with open(self.meta_path, "w") as f:
            json.dump(
                {
                    "rowLabel": self.row_label,
                    "inverseEnabled": self.inverse_enabled,
                    "cacheType": self.cache_type,
                    "cacheSize": self.cache_size,
                    "timeQuantum": self.time_quantum,
                },
                f,
            )

    def apply_options(self, opt: FrameOptions) -> None:
        # Callers validate first (Index._create_frame runs opt.validate()
        # BEFORE any on-disk state exists); this only applies.
        if opt.row_label:
            self.row_label = opt.row_label
        self.inverse_enabled = bool(opt.inverse_enabled)
        if opt.cache_type:
            self.cache_type = opt.cache_type
        if opt.cache_size:
            self.cache_size = opt.cache_size
        if opt.time_quantum:
            self.time_quantum = tq.parse_time_quantum(opt.time_quantum)
        self.save_meta()

    def set_time_quantum(self, q: str) -> None:
        self.time_quantum = tq.parse_time_quantum(q)
        self.save_meta()

    def schema_json(self) -> dict:
        return {
            "name": self.name,
            "rowLabel": self.row_label,
            "inverseEnabled": self.inverse_enabled,
            "cacheType": self.cache_type,
            "cacheSize": self.cache_size,
            "timeQuantum": self.time_quantum,
        }

    # -- views ----------------------------------------------------------

    def view_path(self, name: str) -> str:
        return os.path.join(self.path, "views", name)

    def _open_view(self, name: str) -> View:
        v = View(
            self.view_path(name),
            self.index,
            self.name,
            name,
            cache_type=self.cache_type,
            cache_size=self.cache_size,
            row_attr_store=self.row_attr_store,
            on_new_fragment=self.on_new_fragment,
            stats=self.stats.with_tags(f"view:{name}"),
            ranking_debounce_s=self.ranking_debounce_s,
        )
        v.open()
        self.views[name] = v
        return v

    def view(self, name: str) -> Optional[View]:
        return self.views.get(name)

    def create_view_if_not_exists(self, name: str) -> View:
        # Don't create inverse views (incl. time-quantum inverse
        # sub-views) when disabled (frame.go:413-415 IsInverseView).
        if is_inverse_view(name) and not self.inverse_enabled:
            raise ErrFrameInverseDisabled(f"inverse storage disabled for frame {self.name!r}")
        with self._mu:
            v = self.views.get(name)
            if v is not None:
                return v
            return self._open_view(name)

    def max_slice(self) -> int:
        return max((v.max_slice() for v in list(self.views.values())), default=0)

    def max_inverse_slice(self) -> int:
        v = self.views.get(VIEW_INVERSE)
        return v.max_slice() if v else 0

    # -- bit ops (frame.go:446-525) --------------------------------------

    def set_bit(
        self, name: str, row_id: int, col_id: int, timestamp: Optional[datetime] = None
    ) -> bool:
        if not is_valid_view(name):
            raise ErrInvalidView(f"invalid view: {name}")
        changed = self.create_view_if_not_exists(name).set_bit(row_id, col_id)
        if timestamp is None:
            return changed
        if not self.time_quantum:
            return changed
        for subname in tq.views_by_time(name, timestamp, self.time_quantum):
            if self.create_view_if_not_exists(subname).set_bit(row_id, col_id):
                changed = True
        return changed

    def set_bits(
        self,
        name: str,
        row_ids,
        column_ids,
        timestamps: Optional[Sequence[Optional[datetime]]] = None,
    ) -> "np.ndarray":
        """Durable batched SetBit: per-input changed bools, semantically
        identical to issuing set_bit sequentially (first occurrence of a
        duplicate wins).  One fragment pass + WAL append per touched
        (view, slice) instead of per bit."""
        if not is_valid_view(name):
            raise ErrInvalidView(f"invalid view: {name}")
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        if len(row_ids) != len(column_ids):
            raise ValueError("row/column id length mismatch")
        if timestamps is not None and len(timestamps) != len(row_ids):
            raise ValueError("timestamps length mismatch")
        changed = self.create_view_if_not_exists(name).set_bits(row_ids, column_ids)
        if self.time_quantum and timestamps is not None:
            # Group indices by time sub-view so each sub-view gets one pass.
            by_view: dict[str, list[int]] = {}
            for i, t in enumerate(timestamps):
                if t is None:
                    continue
                for subname in tq.views_by_time(name, t, self.time_quantum):
                    by_view.setdefault(subname, []).append(i)
            for subname, idxs in by_view.items():
                sub_changed = self.create_view_if_not_exists(subname).set_bits(
                    row_ids[idxs], column_ids[idxs]
                )
                changed[idxs] |= sub_changed
        return changed

    def clear_bit(self, name: str, row_id: int, col_id: int) -> bool:
        if not is_valid_view(name):
            raise ErrInvalidView(f"invalid view: {name}")
        v = self.views.get(name)
        if v is None:
            return False
        return v.clear_bit(row_id, col_id)

    # -- bulk import (frame.go:530-606) -----------------------------------

    def import_bits(
        self,
        row_ids: Sequence[int],
        column_ids: Sequence[int],
        timestamps: Optional[Sequence[Optional[datetime]]] = None,
    ) -> None:
        """Group bits by target view and bulk-load per fragment.

        Standard view gets every bit; time views get timestamped bits;
        the inverse view (when enabled) gets the transposed pairs.
        """
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        if timestamps is None:
            timestamps = [None] * len(row_ids)

        # view name -> (rows list, cols list)
        groups: dict[str, tuple[list, list]] = {}

        def add(view_name: str, r: int, c: int):
            g = groups.setdefault(view_name, ([], []))
            g[0].append(r)
            g[1].append(c)

        for r, c, t in zip(row_ids.tolist(), column_ids.tolist(), timestamps):
            add(VIEW_STANDARD, r, c)
            if self.inverse_enabled:
                add(VIEW_INVERSE, c, r)
            if t is not None and self.time_quantum:
                for name in tq.views_by_time(VIEW_STANDARD, t, self.time_quantum):
                    add(name, r, c)
                if self.inverse_enabled:
                    for name in tq.views_by_time(VIEW_INVERSE, t, self.time_quantum):
                        add(name, c, r)

        for view_name, (rows, cols) in groups.items():
            view = self.create_view_if_not_exists(view_name)
            rows = np.asarray(rows, dtype=np.uint64)
            cols = np.asarray(cols, dtype=np.uint64)
            slices = cols // np.uint64(SLICE_WIDTH)
            for slice_i in np.unique(slices):
                mask = slices == slice_i
                frag = view.create_fragment_if_not_exists(int(slice_i))
                frag.import_bits(rows[mask], cols[mask])
