"""View: a named bitmap matrix within a frame, split into per-slice fragments.

Reference analog: view.go.  Names: "standard", "inverse", and time-quantum
suffixed forms like "standard_2017" (view.go:31-34).  A view routes global
column ids to fragments by ``slice = columnID // SLICE_WIDTH``
(view.go:266-283) and notifies the server (for CreateSliceMessage
broadcast) when a fragment for a new max slice appears (view.go:219-254).
"""

from __future__ import annotations

import os
import threading

from pilosa_tpu.analysis import lockcheck
from typing import Callable, Optional

from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core.fragment import DEFAULT_CACHE_SIZE, Fragment
from pilosa_tpu.pilosa import SLICE_WIDTH

VIEW_STANDARD = "standard"
VIEW_INVERSE = "inverse"


def is_valid_view(name: str) -> bool:
    return name in (VIEW_STANDARD, VIEW_INVERSE)


def is_inverse_view(name: str) -> bool:
    """The base inverse view or any time-quantum inverse sub-view
    (view.go IsInverseView prefix semantics)."""
    return name == VIEW_INVERSE or name.startswith(VIEW_INVERSE + "_")


class View:
    def __init__(
        self,
        path: str,
        index: str,
        frame: str,
        name: str,
        cache_type: str = cache_mod.DEFAULT_CACHE_TYPE,
        cache_size: int = DEFAULT_CACHE_SIZE,
        row_attr_store=None,
        on_new_fragment: Optional[Callable[[str, str, str, int], None]] = None,
        stats=None,
        ranking_debounce_s=None,
    ):
        self.path = path
        self.index = index
        self.frame = frame
        self.name = name
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.ranking_debounce_s = ranking_debounce_s
        self.row_attr_store = row_attr_store
        from pilosa_tpu.stats import NOP_STATS

        self.on_new_fragment = on_new_fragment  # broadcast hook (CreateSliceMessage)
        self.stats = stats if stats is not None else NOP_STATS
        # Guards fragment create against concurrent writers (view.go mu analog).
        self._mu = lockcheck.named_rlock("core.view._mu")
        self.fragments: dict[int, Fragment] = {}

    # -- lifecycle ------------------------------------------------------

    def open(self) -> None:
        frag_dir = os.path.join(self.path, "fragments")
        os.makedirs(frag_dir, exist_ok=True)
        for entry in sorted(os.listdir(frag_dir)):
            if not entry.isdigit():
                continue
            self._open_fragment(int(entry))

    def close(self) -> None:
        for f in list(self.fragments.values()):
            f.close()
        self.fragments.clear()

    def flush_caches(self) -> None:
        # list() snapshots: writers may insert fragments concurrently
        for f in list(self.fragments.values()):
            f.flush_cache()

    def fragment_path(self, slice_i: int) -> str:
        return os.path.join(self.path, "fragments", str(slice_i))

    def _open_fragment(self, slice_i: int) -> Fragment:
        f = Fragment(
            self.fragment_path(slice_i),
            self.index,
            self.frame,
            self.name,
            slice_i,
            cache_type=self.cache_type,
            cache_size=self.cache_size,
            row_attr_store=self.row_attr_store,
            stats=self.stats.with_tags(f"slice:{slice_i}"),
            ranking_debounce_s=self.ranking_debounce_s,
        )
        f.open()
        self.fragments[slice_i] = f
        return f

    # -- fragments ------------------------------------------------------

    def fragment(self, slice_i: int) -> Optional[Fragment]:
        return self.fragments.get(slice_i)

    def create_fragment_if_not_exists(self, slice_i: int) -> Fragment:
        with self._mu:
            f = self.fragments.get(slice_i)
            if f is not None:
                return f
            is_new_max = not self.fragments or slice_i > self.max_slice()
            f = self._open_fragment(slice_i)
        if is_new_max:
            self.stats.count("maxSlice", 1)  # view.go:251
            if self.on_new_fragment is not None:
                self.on_new_fragment(self.index, self.frame, self.name, slice_i)
        return f

    def max_slice(self) -> int:
        return max(list(self.fragments.keys()), default=0)

    # -- bit ops (view.go:266-283) ---------------------------------------

    def set_bit(self, row_id: int, column_id: int) -> bool:
        slice_i = column_id // SLICE_WIDTH
        return self.create_fragment_if_not_exists(slice_i).set_bit(row_id, column_id)

    def set_bits(self, row_ids, column_ids):
        """Batched SetBit routed per slice; returns per-input changed bools
        (order preserved).  One fragment pass + WAL append per slice."""
        import numpy as np

        row_ids = np.asarray(row_ids, dtype=np.uint64)
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        if len(row_ids) != len(column_ids):
            raise ValueError("row/column id length mismatch")
        changed = np.zeros(len(row_ids), dtype=bool)
        if len(row_ids) <= 8:
            # Tiny batches (group-commit queue): plain-python slice
            # grouping — the vectorized unique/nonzero/fancy-index route
            # below costs ~40 us of numpy dispatch per call.
            by_slice: dict[int, list[int]] = {}
            cols = column_ids.tolist()
            for i, c in enumerate(cols):
                by_slice.setdefault(c // SLICE_WIDTH, []).append(i)
            rows = row_ids.tolist()
            for s, idx in by_slice.items():
                frag = self.create_fragment_if_not_exists(s)
                ch = frag.set_bits(
                    np.asarray([rows[i] for i in idx], dtype=np.uint64),
                    np.asarray([cols[i] for i in idx], dtype=np.uint64),
                )
                for k, i in enumerate(idx):
                    changed[i] = ch[k]
            return changed
        slices = (column_ids // np.uint64(SLICE_WIDTH)).astype(np.int64)
        for s in np.unique(slices).tolist():
            idx = np.nonzero(slices == s)[0]
            frag = self.create_fragment_if_not_exists(int(s))
            changed[idx] = frag.set_bits(row_ids[idx], column_ids[idx])
        return changed

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        slice_i = column_id // SLICE_WIDTH
        f = self.fragments.get(slice_i)
        if f is None:
            return False
        return f.clear_bit(row_id, column_id)
