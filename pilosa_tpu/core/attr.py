"""Attribute storage: id -> typed attribute maps.

Reference analog: attr.go — a BoltDB-backed KV store of protobuf attr maps
with an in-memory cache (attr.go:43-178), typed values
string/int/bool/float (attr.go:35-40), and anti-entropy via SHA1 checksums
over blocks of 100 ids (attr.go:181-241, AttrBlocks.Diff attr.go:394-428).

This build uses sqlite3 (stdlib, durable, transactional) as the KV engine
and JSON for the typed value encoding; block checksums hash the canonical
JSON so replicas agree byte-for-byte.
"""

from __future__ import annotations

import hashlib
import json
import os
import sqlite3
import threading

from pilosa_tpu.analysis import lockcheck
from typing import Optional

ATTR_BLOCK_SIZE = 100


def _canonical(attrs: dict) -> bytes:
    return json.dumps(attrs, sort_keys=True, separators=(",", ":")).encode()


def _validate_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if not isinstance(k, str):
            raise TypeError(f"attribute key must be str: {k!r}")
        if v is None or isinstance(v, (str, bool, int, float)):
            out[k] = v
        else:
            raise TypeError(f"unsupported attribute value type: {k}={v!r}")
    return out


class AttrStore:
    """Durable id->attrs store with in-memory cache (attr.go:43)."""

    def __init__(self, path: str):
        self.path = path
        self._cache: dict[int, dict] = {}
        self._lock = lockcheck.named_rlock("core.attrstore._lock")
        self._db: Optional[sqlite3.Connection] = None

    def open(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._db = sqlite3.connect(self.path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS attrs (id INTEGER PRIMARY KEY, data TEXT NOT NULL)"
        )
        self._db.commit()

    def close(self) -> None:
        if self._db is not None:
            self._db.close()
            self._db = None
        self._cache.clear()

    def attrs(self, id: int) -> Optional[dict]:
        with self._lock:
            if id in self._cache:
                return self._cache[id]
            row = self._db.execute("SELECT data FROM attrs WHERE id=?", (int(id),)).fetchone()
            attrs = json.loads(row[0]) if row else None
            if attrs is not None:
                self._cache[id] = attrs
            return attrs

    def set_attrs(self, id: int, attrs: dict) -> dict:
        """Merge attrs into the stored map; None values delete keys
        (attr.go SetAttrs merge semantics)."""
        attrs = _validate_attrs(attrs)
        with self._lock:
            cur = self.attrs(id) or {}
            merged = dict(cur)
            for k, v in attrs.items():
                if v is None:
                    merged.pop(k, None)
                else:
                    merged[k] = v
            self._db.execute(
                "INSERT OR REPLACE INTO attrs (id, data) VALUES (?, ?)",
                (int(id), _canonical(merged).decode()),
            )
            self._db.commit()
            self._cache[id] = merged
            return merged

    def set_bulk_attrs(self, items: dict[int, dict]) -> None:
        with self._lock:
            for id, attrs in items.items():
                self.set_attrs(id, attrs)

    def ids(self) -> list[int]:
        rows = self._db.execute("SELECT id FROM attrs ORDER BY id").fetchall()
        return [r[0] for r in rows]

    # -- anti-entropy blocks (attr.go:181-241) --------------------------

    def blocks(self) -> list[tuple[int, bytes]]:
        """(block id, sha1) over blocks of ATTR_BLOCK_SIZE ids."""
        rows = self._db.execute("SELECT id, data FROM attrs ORDER BY id").fetchall()
        out: list[tuple[int, bytes]] = []
        h = None
        cur_block = None
        for id, data in rows:
            bid = id // ATTR_BLOCK_SIZE
            if bid != cur_block:
                if h is not None:
                    out.append((cur_block, h.digest()))
                cur_block, h = bid, hashlib.sha1()
            h.update(str(id).encode())
            h.update(data.encode())
        if h is not None:
            out.append((cur_block, h.digest()))
        return out

    def block_data(self, block_id: int) -> dict[int, dict]:
        rows = self._db.execute(
            "SELECT id, data FROM attrs WHERE id >= ? AND id < ? ORDER BY id",
            (block_id * ATTR_BLOCK_SIZE, (block_id + 1) * ATTR_BLOCK_SIZE),
        ).fetchall()
        return {id: json.loads(data) for id, data in rows}


def blocks_diff(local: list[tuple[int, bytes]], remote: list[tuple[int, bytes]]) -> list[int]:
    """Block ids present/differing in remote vs local (attr.go:394-428)."""
    lm = dict(local)
    out = []
    for bid, chk in remote:
        if lm.get(bid) != chk:
            out.append(bid)
    return sorted(out)
