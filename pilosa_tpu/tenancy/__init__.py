"""Multi-tenant isolation and fairness (ROADMAP item 5).

The reference serves many indexes from one cluster over HTTP; at
"millions of users" scale that is a shared service with hostile
neighbors.  PR 13 gave every trace span, slow-query log line, and
cost-ledger entry a ``tenant`` tag but enforced nothing — one tenant's
flood degraded every tenant's p99, evicted everyone's qcache entries,
and saturated the ingest doors.  This subsystem turns the attribution
into isolation, on the seams the earlier PRs left open:

- :func:`resolve` — the SINGLE tenant-resolution seam, shared by the
  HTTP handler, the lockstep front end (resolved once on rank 0, riding
  the batch wire entry like the expired/trace/plan flags so every rank
  agrees), and the replica router.  Precedence: ``X-Pilosa-Tenant``
  header > explicit ``[tenancy] map`` index→tenant table > index name >
  ``"default"``.
- :class:`FairShare` — weighted fair-share admission accounting INSIDE
  the existing QoS class doors (qos/admission.py).  Each tenant's
  inflight share of a door's depth is ``depth * w_t / W_active`` where
  ``W_active`` sums the weights of tenants at the door — inflight,
  waiting, or active within a short presence window so a tenant's
  between-requests instant never hands its share to a flooder
  (work-conserving at the window's horizon: a tenant alone gets the
  whole depth, a departed tenant's share is reclaimed).  A tenant
  over its share sheds 429 + Retry-After while under-share tenants keep
  clearing the same door; per-admit deficit (``1/w_t``) accumulates as
  the billing-adjacent debt series /debug/tenants exposes.
- :class:`BandwidthPacer` — per-tenant token buckets on the streaming
  ingest and device-bulk chunk doors so a backfill cannot starve
  interactive writes (``[tenancy] ingest-bytes-per-s``).
- :class:`TenancyState` — the per-server aggregate built from the
  ``[tenancy]`` config section: resolution map + weights + qcache byte
  shares + pacer, handed to the handler, the admission controller, the
  query cache, and the replica router.

Isolation OFF (the default — ``[tenancy] enabled = false``) is the
contract the rest of the tree relies on: no TenancyState is built and
every touched seam takes its pre-tenancy path byte-identically.
"""

from __future__ import annotations

import re
import time
from typing import Optional

from pilosa_tpu.analysis import lockcheck

# Client tenant override header (case-insensitive on the wire; handler
# dicts are lowercased).
TENANT_HEADER = "X-Pilosa-Tenant"
DEFAULT_TENANT = "default"

_INDEX_RX = re.compile(r"^/index/([^/]+)")


def index_of(path: str) -> str:
    """The index an ``/index/<name>/...`` request addresses, or ""."""
    m = _INDEX_RX.match(path or "")
    return m.group(1) if m else ""


def resolve(path: str, headers=None, index_map=None,
            default: str = DEFAULT_TENANT) -> str:
    """The single tenant-resolution seam (see module docstring).

    Precedence: ``X-Pilosa-Tenant`` header > ``index_map`` entry for the
    addressed index > the index name itself > ``default`` (admin routes
    with no index).  Every door that attributes OR enforces goes through
    this function so trace tags, slow-query lines, the cost ledger, and
    the admission doors can never disagree on a request's tenant.
    """
    if headers:
        hdr = (headers.get(TENANT_HEADER.lower()) or "").strip()
        if hdr:
            return hdr
    index = index_of(path)
    if index:
        if index_map:
            mapped = index_map.get(index)
            if mapped:
                return mapped
        return index
    return default


# -- config parsing ---------------------------------------------------------


def parse_weights(s) -> dict[str, float]:
    """``"gold=4,free=1"`` -> {"gold": 4.0, "free": 1.0}."""
    out: dict[str, float] = {}
    for part in str(s or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            out[name.strip()] = max(1e-3, float(val))
        except ValueError:
            continue
    return out


def parse_map(s) -> dict[str, str]:
    """``"idx_a=gold,idx_b=free"`` -> {"idx_a": "gold", ...}."""
    out: dict[str, str] = {}
    for part in str(s or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        index, _, tenant = part.partition("=")
        if index.strip() and tenant.strip():
            out[index.strip()] = tenant.strip()
    return out


def parse_shares(s) -> tuple[float, dict[str, float]]:
    """qcache-share config: a bare fraction ("0.5" — every tenant may
    hold at most half the cache) or per-tenant overrides
    ("gold=0.75,free=0.1").  Returns (default_share, per-tenant map);
    0.0 means unquoted (no per-tenant byte cap)."""
    s = str(s or "").strip()
    if not s:
        return 0.0, {}
    if "=" not in s:
        try:
            return min(1.0, max(0.0, float(s))), {}
        except ValueError:
            return 0.0, {}
    out: dict[str, float] = {}
    for part in s.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            out[name.strip()] = min(1.0, max(0.0, float(val)))
        except ValueError:
            continue
    return 0.0, out


# -- weighted fair-share admission accounting -------------------------------


@lockcheck.guarded_class
class FairShare:
    """Per-tenant deficit-weighted accounting inside the QoS doors.

    PASSIVE by design: every method is called by AdmissionController
    with the door's ``_cv`` already held, so the accounting joins the
    door's existing critical section instead of adding a second lock to
    the admission fast path — the declarations below make that contract
    checkable (lockcheck's lockset race detector sees every rebind, the
    static guarded-fields rule covers the in-place dict mutations via
    the locked caller chain in qos/admission.py).
    """

    # Presence hysteresis: a tenant stays "present" at the door for this
    # long after its last admit/wait/release, so the instant between a
    # closed-loop client's release and its next request does NOT hand
    # its whole share to a flooder (which would then hold depth slots
    # for a full drain — exactly the burst-seizure real weighted-fair
    # schedulers smooth away).  Work conservation still holds at the
    # window's horizon: half a second after a tenant truly leaves, the
    # remaining tenants split its share.
    PRESENCE_S = 0.5

    _guarded_by_ = {
        "_inflight": "qos.admission._cv",
        "_waiting": "qos.admission._cv",
        "_seen": "qos.admission._cv",
        "_debt": "qos.admission._cv",
        "_admitted": "qos.admission._cv",
        "_shed": "qos.admission._cv",
    }

    def __init__(self, weights=None, default_weight: float = 1.0, clock=time.monotonic):
        self.weights = {k: max(1e-3, float(v)) for k, v in (weights or {}).items()}
        self.default_weight = max(1e-3, float(default_weight))
        self._clock = clock
        # cls -> tenant -> count (entries removed at zero so "present at
        # the door" is exactly the key set).
        self._inflight: dict[str, dict[str, int]] = {}
        self._waiting: dict[str, dict[str, int]] = {}
        # cls -> tenant -> last door activity (monotonic): the recency
        # half of "present" (see PRESENCE_S).
        self._seen: dict[str, dict[str, float]] = {}
        # Lifetime totals (per tenant, across classes).
        self._debt: dict[str, float] = {}
        self._admitted: dict[str, int] = {}
        self._shed: dict[str, int] = {}

    def _touch(self, cls: str, tenant: str) -> None:
        self._seen.setdefault(cls, {})[tenant] = self._clock()

    def weight(self, tenant: str) -> float:
        return self.weights.get(tenant, self.default_weight)

    def cap(self, cls: str, tenant: str, depth: int) -> int:
        """The tenant's inflight share of one door: a weighted split of
        ``depth`` over the tenants PRESENT at the door (inflight,
        waiting, or active within PRESENCE_S, plus the asker) —
        work-conserving at the hysteresis horizon: a tenant alone gets
        the whole depth, shares rebalance the moment a neighbor shows
        up, and a departed tenant's share is reclaimed PRESENCE_S after
        its last activity.  Never below 1: presence always buys
        eventual progress."""
        seen = self._seen.get(cls)
        recent: set = set()
        if seen:
            horizon = self._clock() - self.PRESENCE_S
            stale = [t for t, ts in seen.items() if ts < horizon]
            for t in stale:
                del seen[t]
            recent = set(seen)
        present = (
            set(self._inflight.get(cls, ()))
            | set(self._waiting.get(cls, ()))
            | recent
            | {tenant}
        )
        w_all = sum(self.weight(t) for t in present)
        if w_all <= 0.0:
            return depth
        return max(1, int(depth * self.weight(tenant) / w_all))

    def over_cap(self, cls: str, tenant: str, depth: int) -> bool:
        return self._inflight.get(cls, {}).get(tenant, 0) >= self.cap(
            cls, tenant, depth
        )

    def wait_full(self, cls: str, tenant: str, depth: int) -> bool:
        """Per-tenant wait-lane bound: a flooding tenant may queue at
        most its own share of waiters, so it can never fill the lane
        and shed a polite tenant at the door."""
        return self._waiting.get(cls, {}).get(tenant, 0) >= self.cap(
            cls, tenant, depth
        )

    def note_wait(self, cls: str, tenant: str, delta: int) -> None:
        self._touch(cls, tenant)
        by = self._waiting.setdefault(cls, {})
        n = by.get(tenant, 0) + delta
        if n <= 0:
            by.pop(tenant, None)
        else:
            by[tenant] = n

    def note_admit(self, cls: str, tenant: str) -> None:
        self._touch(cls, tenant)
        by = self._inflight.setdefault(cls, {})
        by[tenant] = by.get(tenant, 0) + 1
        # Deficit-weighted debt: each admit costs 1/w_t, so equal debt
        # growth means weight-proportional admission (the /debug/tenants
        # fairness probe and the billing-adjacent usage series).
        self._debt[tenant] = self._debt.get(tenant, 0.0) + 1.0 / self.weight(tenant)
        self._admitted[tenant] = self._admitted.get(tenant, 0) + 1

    def note_release(self, cls: str, tenant: str) -> None:
        self._touch(cls, tenant)
        by = self._inflight.get(cls)
        if by is None:
            return
        n = by.get(tenant, 0) - 1
        if n <= 0:
            by.pop(tenant, None)
        else:
            by[tenant] = n

    def note_shed(self, cls: str, tenant: str) -> None:
        self._shed[tenant] = self._shed.get(tenant, 0) + 1

    def snapshot(self, depths=None) -> dict:
        """Per-tenant accounting rows (caller holds the door's _cv)."""
        tenants: set[str] = set(self._debt) | set(self._shed)
        for by in self._inflight.values():
            tenants |= set(by)
        for by in self._waiting.values():
            tenants |= set(by)
        out = {}
        for t in sorted(tenants):
            inflight = {
                cls: by[t] for cls, by in self._inflight.items() if t in by
            }
            row = {
                "weight": self.weight(t),
                "inflight": inflight,
                "waiting": {
                    cls: by[t] for cls, by in self._waiting.items() if t in by
                },
                "debt": round(self._debt.get(t, 0.0), 3),
                "admitted": self._admitted.get(t, 0),
                "shed": self._shed.get(t, 0),
            }
            if depths:
                row["share"] = {
                    cls: self.cap(cls, t, depth)
                    for cls, depth in depths.items()
                    if depth > 0
                }
            out[t] = row
        return out


# -- per-tenant ingest/bulk bandwidth pacing --------------------------------


@lockcheck.guarded_class
class BandwidthPacer:
    """Per-tenant token-bucket pacer for the streaming-ingest and bulk
    chunk doors (``[tenancy] ingest-bytes-per-s``).

    Each tenant's refill rate is its weighted share of the aggregate
    budget over the tenants ACTIVE in the last idle window — like the
    admission caps, work-conserving: a lone backfill gets the whole
    budget, and the share rebalances the moment an interactive writer
    shows up.  :meth:`admit` answers 0.0 (chunk admitted, tokens spent)
    or the advised Retry-After seconds; the door maps that to
    429 + Retry-After through the existing ShedError plumbing.
    """

    _guarded_by_ = {"_buckets": "tenancy.pacer._mu"}

    # A bucket idle past this window returns its share to the others.
    IDLE_S = 10.0

    def __init__(self, bytes_per_s: int, weights=None,
                 default_weight: float = 1.0, burst_s: float = 2.0,
                 clock=time.monotonic):
        self.bytes_per_s = max(1, int(bytes_per_s))
        self.weights = {k: max(1e-3, float(v)) for k, v in (weights or {}).items()}
        self.default_weight = max(1e-3, float(default_weight))
        self.burst_s = max(0.1, float(burst_s))
        self._clock = clock
        self._mu = lockcheck.named_lock("tenancy.pacer._mu")
        # tenant -> [tokens, last_refill_ts, last_seen_ts]
        self._buckets: dict[str, list] = {}

    def weight(self, tenant: str) -> float:
        return self.weights.get(tenant, self.default_weight)

    def admit(self, tenant: str, nbytes: int) -> float:
        """Spend ``nbytes`` from the tenant's bucket.  Returns 0.0 when
        the chunk is admitted, else the advised retry-after in seconds
        (never admits partially: the chunk wire retries whole chunks)."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return 0.0
        now = self._clock()
        with self._mu:
            stale = [
                t for t, b in self._buckets.items()
                if t != tenant and now - b[2] > self.IDLE_S
            ]
            for t in stale:
                del self._buckets[t]
            w_all = sum(
                self.weight(t) for t in set(self._buckets) | {tenant}
            )
            rate = self.bytes_per_s * self.weight(tenant) / max(1e-3, w_all)
            # The burst ceiling never drops below one chunk: any single
            # chunk eventually clears, however small the share.
            cap = max(float(nbytes), rate * self.burst_s)
            b = self._buckets.get(tenant)
            if b is None:
                b = self._buckets[tenant] = [cap, now, now]
            tokens = min(cap, b[0] + (now - b[1]) * rate)
            b[1] = now
            b[2] = now
            if tokens >= nbytes:
                b[0] = tokens - nbytes
                return 0.0
            b[0] = tokens
            return max(0.05, (nbytes - tokens) / rate)

    def snapshot(self) -> dict:
        now = self._clock()
        with self._mu:
            return {
                t: {
                    "tokens": int(b[0]),
                    "idleS": round(now - b[2], 3),
                }
                for t, b in self._buckets.items()
            }


# -- the per-server aggregate -----------------------------------------------


class TenancyState:
    """Everything one server's tenancy enforcement shares: resolution
    map, fair-share door accounting, qcache byte shares, ingest pacer.
    Built once from the ``[tenancy]`` config section and handed to the
    handler, the admission controller, the query cache, and the replica
    router; None everywhere = isolation off, byte-identical behavior."""

    def __init__(self, weights=None, default_weight: float = 1.0,
                 index_map=None, qcache_share="", ingest_bytes_per_s: int = 0,
                 stats=None):
        from pilosa_tpu.stats import NOP_STATS

        self.weights = (
            parse_weights(weights) if isinstance(weights, str)
            else {k: max(1e-3, float(v)) for k, v in (weights or {}).items()}
        )
        self.default_weight = max(1e-3, float(default_weight))
        self.index_map = (
            parse_map(index_map) if isinstance(index_map, str)
            else dict(index_map or {})
        )
        self.default_share, self.shares = parse_shares(qcache_share)
        self.stats = stats if stats is not None else NOP_STATS
        self.fair = FairShare(self.weights, self.default_weight)
        self.pacer = (
            BandwidthPacer(
                ingest_bytes_per_s,
                weights=self.weights,
                default_weight=self.default_weight,
            )
            if int(ingest_bytes_per_s or 0) > 0
            else None
        )

    def resolve(self, path: str, headers=None) -> str:
        return resolve(path, headers, self.index_map)

    def resolve_for_index(self, index: str, headers=None) -> str:
        """Resolution for doors that already hold the index name (the
        ingest/bulk chunk wire) — same precedence, no path re-parse."""
        if headers:
            hdr = (headers.get(TENANT_HEADER.lower()) or "").strip()
            if hdr:
                return hdr
        return self.tenant_of_index(index)

    def tenant_of_index(self, index: str) -> str:
        if not index:
            return DEFAULT_TENANT
        return self.index_map.get(index, index)

    def qcache_quota(self, tenant: str, max_bytes: int) -> int:
        """The tenant's qcache byte quota; 0 = unquoted."""
        share = self.shares.get(tenant, self.default_share)
        if share <= 0.0:
            return 0
        return int(max_bytes * share)


def from_config(cfg, stats=None) -> Optional[TenancyState]:
    """Build the tenancy state from a Config, or None when the
    ``[tenancy]`` section is disabled (the default)."""
    if not getattr(cfg, "tenancy_enabled", False):
        return None
    return TenancyState(
        weights=cfg.tenancy_weights,
        default_weight=cfg.tenancy_default_weight,
        index_map=cfg.tenancy_map,
        qcache_share=cfg.tenancy_qcache_share,
        ingest_bytes_per_s=cfg.tenancy_ingest_bytes_per_s,
        stats=stats,
    )
