"""Compute engines for batched slice evaluation.

The executor evaluates a PQL bitmap-call tree over a *batch* of slices at
once: leaves gather dense rows into a ``uint32[n_slices, W]`` matrix and
set ops/counts apply to the whole stack in one call.  The engine decides
where that matrix lives:

- `JaxEngine` — jnp arrays on the default JAX backend; fused counts go
  through pilosa_tpu.ops.dispatch (Pallas on TPU).  This is the production
  path: one device dispatch per query stage for *all* local slices, the
  TPU-native replacement for the reference's goroutine-per-slice fan-out
  (executor.go:1209-1244).
- `NumpyEngine` — pure numpy; used for tests, TPU-less hosts, and tiny
  working sets where a device round-trip costs more than the op.

Both satisfy the same small protocol; results surface as numpy.
"""

from __future__ import annotations

import os

import numpy as np

from pilosa_tpu.roaring import _POPCNT8

# Pair-op table for the numpy engine.  Deliberately NOT shared with
# ops.bitwise.apply_pair_op: importing ops.bitwise pulls in jax at module
# top, and the numpy engine must work on hosts where jax is absent/broken.
_NP_OPS = {
    "and": lambda a, b: a & b,
    "or": lambda a, b: a | b,
    "xor": lambda a, b: a ^ b,
    "andnot": lambda a, b: a & ~b,
}

# Tree-fold opcodes by id (ops.bitwise.gather_count_tree encoding);
# opcode 4 = PASS (take the left child — perfect-tree padding).
_TREE_NP_OPS = {
    0: _NP_OPS["and"],
    1: _NP_OPS["or"],
    2: _NP_OPS["xor"],
    3: _NP_OPS["andnot"],
    4: lambda a, b: a,
}


def nbytes(*arrays) -> int:
    """Total byte size of the given arrays (None entries skipped) — the
    dispatch meter's operand/transfer accounting.  Works for numpy and
    jax arrays alike (both expose .nbytes)."""
    total = 0
    for a in arrays:
        if a is None:
            continue
        n = getattr(a, "nbytes", None)
        if n is None:
            n = getattr(a, "size", 0) * getattr(a, "itemsize", 0)
        total += int(n)
    return total


class NumpyEngine:
    name = "numpy"
    # No jit: callers may use exact (ragged) dispatch shapes freely.
    wants_static_shapes = False
    # Host == device on numpy: nothing ever crosses a transfer boundary,
    # so the upload ledger stays at zero (class attr, never mutated).
    stat_upload_bytes = 0

    def stack(self, rows: list[np.ndarray]) -> np.ndarray:
        return np.stack(rows) if rows else np.zeros((0, 0), dtype=np.uint32)

    def stack_rows(self, rows: list) -> np.ndarray:
        """Stack engine-resident rows (same as stack on numpy)."""
        return self.stack(rows)

    def stack_slices(self, stacks: list) -> np.ndarray:
        """Stack along the SLICE axis (mesh engines shard this one)."""
        return self.stack(stacks)

    def asarray(self, x: np.ndarray):
        return np.asarray(x)

    def matrix(self, host_matrix: np.ndarray):
        """Move a fully-assembled host row matrix [n_slices, n_rows, W]
        into engine storage in ONE transfer (vs per-row uploads)."""
        return host_matrix

    def gather_count_and(self, row_matrix, pairs) -> np.ndarray:
        """Batched Count(Intersect) over [n_slices, n_rows, W] for int32[B,2]
        row-index pairs; returns int64[B]."""
        return self.gather_count("and", row_matrix, pairs)

    def gather_count(self, op: str, row_matrix, pairs) -> np.ndarray:
        """Batched Count(<op>(...)) — and/or/xor/andnot pair counts."""
        a = row_matrix[:, pairs[:, 0], :]
        b = row_matrix[:, pairs[:, 1], :]
        r = _NP_OPS[op](a, b)
        return self.count(r).sum(axis=0)

    def gather_count_multi(self, op: str, row_matrix, idx) -> np.ndarray:
        """Batched Count over a left-fold of K gathered rows — N-operand
        Intersect/Union/Difference and the fused Range cover (op="or").
        idx: int32[B, K], padded with fold-idempotent ids.  Returns
        int64[B].

        Chunked over the batch so the gathered [S, chunk, K, W] stays a
        few MB — one shot over the whole batch would materialize
        S*B*K*W*4 bytes (easily hundreds of MB) for nothing.
        """
        from pilosa_tpu.pilosa import OR_MULTI_BUDGET_HOST, or_multi_chunk_size

        s, _, w = row_matrix.shape
        k = idx.shape[1]
        chunk = or_multi_chunk_size(s, k, w, OR_MULTI_BUDGET_HOST)
        out = np.empty(idx.shape[0], dtype=np.int64)
        for i in range(0, idx.shape[0], chunk):
            g = row_matrix[:, idx[i : i + chunk], :]
            if op == "or":
                acc = np.bitwise_or.reduce(g, axis=2)
            elif op == "and":
                acc = np.bitwise_and.reduce(g, axis=2)
            elif op == "andnot":
                acc = g[:, :, 0] & ~np.bitwise_or.reduce(g[:, :, 1:], axis=2)
            else:
                raise ValueError(f"unsupported multi-op {op!r}")
            out[i : i + chunk] = self.count(acc).sum(axis=0)
        return out

    def gather_count_or_multi(self, row_matrix, idx) -> np.ndarray:
        return self.gather_count_multi("or", row_matrix, idx)

    def gather_count_tree(self, row_matrix, leaves, opc) -> np.ndarray:
        """Batched Count over arbitrary nested expression trees (perfect-
        tree encoding, see ops.bitwise.gather_count_tree).  Chunked over
        the batch like gather_count_multi (same transient bound).

        Implemented inline (not via ops.bitwise) for two reasons: the
        numpy engine must work on jax-less hosts (bitwise imports jax at
        module top), and per-node opcode GROUPING does one bitwise pass
        per node — the where-select form evaluates all four ops per node,
        which XLA fuses away but a host loop pays for real.
        """
        from pilosa_tpu.pilosa import OR_MULTI_BUDGET_HOST, or_multi_chunk_size

        s, _, w = row_matrix.shape
        b, k = leaves.shape
        chunk = or_multi_chunk_size(s, k, w, OR_MULTI_BUDGET_HOST)
        out = np.empty(b, dtype=np.int64)
        for i in range(0, b, chunk):
            g = row_matrix[:, leaves[i : i + chunk], :]  # [S, c, K, W]
            oc = opc[i : i + chunk]
            off = 0
            n = k // 2
            while n >= 1:
                a = g[:, :, 0::2]
                bb = g[:, :, 1::2]
                nxt = np.empty_like(a)
                for t in range(n):
                    col = oc[:, off + t]
                    for o in np.unique(col):
                        m = col == o
                        nxt[:, m, t] = _TREE_NP_OPS[int(o)](a[:, m, t], bb[:, m, t])
                g = nxt
                off += n
                n //= 2
            out[i : i + chunk] = self.count(g[:, :, 0]).sum(axis=0)
        return out

    def gather_count_dev(self, op: str, row_matrix, pairs):
        """Like gather_count but returns an ENGINE array without forcing a
        host sync — slice-streaming accumulates these so the next chunk's
        upload overlaps the previous chunk's compute."""
        return self.gather_count(op, row_matrix, pairs)

    def gather_count_multi_dev(self, op: str, row_matrix, idx):
        return self.gather_count_multi(op, row_matrix, idx)

    def gather_count_tree_dev(self, row_matrix, leaves, opc):
        return self.gather_count_tree(row_matrix, leaves, opc)

    def bit_and(self, a, b):
        return a & b

    def bit_or(self, a, b):
        return a | b

    def bit_xor(self, a, b):
        return a ^ b

    def bit_andnot(self, a, b):
        return a & ~b

    def zeros_like(self, a):
        return np.zeros_like(a)

    def count(self, batch) -> np.ndarray:
        """Per-slice popcounts over the last axis (LUT-based, vectorized)."""
        if batch.size == 0:
            return np.zeros(batch.shape[:-1], dtype=np.int64)
        counts = _POPCNT8[np.ascontiguousarray(batch).view(np.uint8)]
        return counts.reshape(*batch.shape[:-1], -1).sum(axis=-1, dtype=np.int64)

    def batch_intersection_count(self, rows, src, tiled: bool = False) -> np.ndarray:
        if tiled:  # trailing [W/128, 128] word axes -> logical [..., W]
            rows = rows.reshape(*rows.shape[:-2], -1)
            src = src.reshape(*src.shape[:-2], -1)
        return self.count(rows & src)

    # Row-major gather lane: no benefit on host (numpy transposes are
    # views), so the executor keeps slice-major transients.
    supports_row_major_gather = False

    def update_slices(self, matrix, slice_idxs, planes):
        """Functionally replace whole slice planes of a row matrix
        (incremental refresh of a cached matrix after writes)."""
        out = matrix.copy()
        out[list(slice_idxs)] = planes
        return out

    def append_rows(self, matrix, block):
        """Append new rows (axis 1) to a row matrix: [S, R, W] + [S, R', W]."""
        return np.concatenate([matrix, block], axis=1)

    def set_rows(self, matrix, row_start: int, block):
        """Functionally write a block of rows at [.., row_start:, ..] —
        fills preallocated capacity without changing the matrix shape
        (shape changes would recompile jitted kernels downstream)."""
        out = matrix.copy()
        out[:, row_start : row_start + block.shape[1], :] = block
        return out

    def set_rows_at(self, matrix, slots, block):
        """Functionally write rows into ARBITRARY slots (row-pool paging:
        a miss batch scatters into freed slots in one call)."""
        out = matrix.copy()
        out[:, list(slots), :] = block
        return out

    def grow_rows(self, matrix, n: int):
        """Append n zero rows of capacity (row-pool doubling)."""
        s, _, w = matrix.shape
        return np.concatenate(
            [matrix, np.zeros((s, n, w), dtype=matrix.dtype)], axis=1
        )

    def set_plane_rows(self, matrix, slice_idxs, slots, block):
        """Functionally write block[i, j] into (slice_idxs[i], slots[j]) —
        the stale-plane refresh touches only RESIDENT slots, transferring
        resident-rows x stale-slices bytes, not whole capacity planes."""
        out = matrix.copy()
        out[np.ix_(list(slice_idxs), list(slots))] = block
        return out

    def build_planes(self, rows, cols):
        """Bulk sort/segment/scatter build: (row, col) uint64 columns ->
        ``(slice_ids, row_ids, planes uint32[G, W])`` — the device-layout
        word planes the bulk ingest door commits into fragments.  Host
        twin (vectorized numpy); the jax engine runs the same contract on
        device."""
        from pilosa_tpu.bulk.build import build_planes_numpy

        return build_planes_numpy(rows, cols)

    def build_words(self, rows, cols):
        """Sparse form of :meth:`build_planes` (CSR over nonzero plane
        words) — the commit path prefers it on host, where scattering
        a chunk's few-hundred touched words per plane beats
        materializing full planes.  The jax engines deliberately do NOT
        implement this: their scatter output is born dense on device."""
        from pilosa_tpu.bulk.build import build_words_numpy

        return build_words_numpy(rows, cols)

    def pair_gram(self, matrix):
        """All-pairs AND-count Gram, or None when unsupported (host
        all-pairs popcount would dwarf the direct path)."""
        return None

    def gram_update_rows(self, matrix, gram, slots, old_matrix=None, slice_idxs=None):
        """Rank-k repair of a host AND-count Gram after in-place row
        rewrites: recompute ONLY the dirty rows/columns with one batched
        pair-count pass against the (already patched) resident matrix —
        O(K*R*W) instead of the O(R^2*W) full rebuild.  Returns a NEW
        array (copy-on-write: readers holding the old Gram keep a
        consistent pre-write snapshot; AND is symmetric, so one K x R
        count block fills both the rows and the columns).

        Per-(row, slice) delta mode: with ``old_matrix`` (the pre-patch
        snapshot) and ``slice_idxs`` (the slice planes actually written),
        the dirty rows' counts are ADJUSTED by (new - old) restricted to
        those slices instead of recomputed over the whole span —
        unchanged slices cancel out of the difference, so the dispatch
        covers K x R x |dirty slices| instead of K x R x S.  Falls back
        to the full recompute when the restriction wouldn't pay
        (>= half the slices dirty)."""
        slots = np.asarray(sorted({int(s) for s in slots}), dtype=np.int64)
        n = gram.shape[0]
        pairs = np.empty((len(slots) * n, 2), dtype=np.int32)
        pairs[:, 0] = np.repeat(slots.astype(np.int32), n)
        pairs[:, 1] = np.tile(np.arange(n, dtype=np.int32), len(slots))
        si = sorted({int(s) for s in slice_idxs}) if slice_idxs is not None else None
        if old_matrix is not None and si and 2 * len(si) < matrix.shape[0]:
            new_c = np.asarray(self.gather_count("and", matrix[si], pairs))
            old_c = np.asarray(self.gather_count("and", old_matrix[si], pairs))
            delta = (new_c.astype(np.int64) - old_c.astype(np.int64)).reshape(
                len(slots), n
            )
            block = (np.asarray(gram)[slots, :] + delta).astype(gram.dtype)
        else:
            block = (
                np.asarray(self.gather_count("and", matrix, pairs))
                .reshape(len(slots), n)
                .astype(gram.dtype)
            )
        out = np.array(gram, copy=True)
        out[slots, :] = block
        out[:, slots] = block.T
        return out

    def to_numpy(self, x) -> np.ndarray:
        return np.asarray(x)


class JaxEngine:
    name = "jax"
    # Jitted kernels recompile per distinct shape (seconds each on TPU):
    # callers should pad dispatch shapes to canonical buckets.
    wants_static_shapes = True

    def __init__(self):
        import jax.numpy as jnp  # deferred so numpy-only paths never init jax

        from pilosa_tpu.ops import dispatch

        self._jnp = jnp
        self._dispatch = dispatch
        # Running host->device transfer ledger (bytes), bumped at every
        # upload seam (matrix/block/src uploads).  A plain int under the
        # GIL; the executor's dispatch meter reads deltas around engine
        # calls to attribute transfer bytes per dispatch.
        self.stat_upload_bytes = 0

    def stack(self, rows: list[np.ndarray]):
        return self._jnp.asarray(np.stack(rows)) if rows else self._jnp.zeros((0, 0), dtype=self._jnp.uint32)

    def stack_rows(self, rows: list):
        """Stack device-resident rows WITHOUT a host round trip — rows from
        the fragment device cache stay in HBM (device-side concat)."""
        if not rows:
            return self._jnp.zeros((0, 0), dtype=self._jnp.uint32)
        return self._jnp.stack([self._jnp.asarray(r) for r in rows])

    def stack_slices(self, stacks: list):
        """Stack along the SLICE axis (mesh engines shard this one)."""
        return self.stack_rows(stacks)

    def asarray(self, x):
        return self._jnp.asarray(x)

    @staticmethod
    def _tile_host(block: np.ndarray) -> np.ndarray:
        """Host-side reshape [..., W] -> [..., W/128, 128] (free: a numpy
        view).  Jax engines store row matrices in this TILED form so the
        Pallas kernels never reshape them inside jit — an in-jit
        [S, R, W] -> [S, R, W/128, 128] reshape changes the physical
        (8, 128) tiling and XLA materializes a full HBM copy of the
        matrix (the round-2 1024-slice OOM; BASELINE.md round-3 note)."""
        if block.shape[-1] % 128:
            return block  # non-tileable widths stay logical (jnp fallback)
        return block.reshape(*block.shape[:-1], block.shape[-1] // 128, 128)

    def matrix(self, host_matrix: np.ndarray):
        """One host→device transfer for an assembled row matrix, stored in
        canonical tiled form uint32[S, R, W/128, 128]."""
        self.stat_upload_bytes += host_matrix.nbytes
        return self._jnp.asarray(self._tile_host(host_matrix))

    def gather_count_and(self, row_matrix, pairs) -> np.ndarray:
        """Batched Count(Intersect) in ONE device dispatch (Pallas on TPU)."""
        return self.gather_count("and", row_matrix, pairs)

    def gather_count(self, op: str, row_matrix, pairs) -> np.ndarray:
        # allow_gram=False: eager per-request dispatch can't amortize the
        # all-pairs matmul; the executor's generation-cached Gram
        # (pair_gram) is the product-path version of that strategy.
        out = self._dispatch.gather_count(
            op, self._jnp.asarray(row_matrix), self._jnp.asarray(pairs), allow_gram=False
        )
        return self.to_numpy(out).astype(np.int64)

    def gather_count_multi(self, op: str, row_matrix, idx) -> np.ndarray:
        out = self._dispatch.gather_count_multi(
            op, self._jnp.asarray(row_matrix), self._jnp.asarray(idx)
        )
        return self.to_numpy(out).astype(np.int64)

    def gather_count_or_multi(self, row_matrix, idx) -> np.ndarray:
        return self.gather_count_multi("or", row_matrix, idx)

    def gather_count_dev(self, op: str, row_matrix, pairs):
        """Async variant: the dispatch is enqueued and the device array
        returned un-fetched, so a streaming loop pipelines chunk k+1's
        host->device upload behind chunk k's kernel."""
        return self._dispatch.gather_count(
            op, self._jnp.asarray(row_matrix), self._jnp.asarray(pairs), allow_gram=False
        )

    # -- row-major gather lane (streaming regime's tall row sets) --------

    @property
    def supports_row_major_gather(self) -> bool:
        # Only worth it where the Pallas kernel runs (TPU): elsewhere the
        # rowmajor dispatch just transposes back per chunk — a pure cost.
        return self._dispatch.use_pallas()

    def matrix_rows(self, host_matrix: np.ndarray):
        """Upload a ROW-MAJOR [R, S, W] host block in tiled form — the
        layout whose per-row bytes are one contiguous DMA descriptor
        (dispatch.gather_count_rowmajor)."""
        self.stat_upload_bytes += host_matrix.nbytes
        return self._jnp.asarray(self._tile_host(host_matrix))

    def rowmajor_ok(self, n_slices: int, words: int, k: int = 2) -> bool:
        return self._dispatch.rowmajor_ok(n_slices, words, k)

    def prefer_rowmajor(
        self, n_rows: int, n_slices: int, words: int, n_pairs: int, max_k: int
    ) -> bool:
        """Whether a resident working set of ``n_rows`` rows should live
        in a ROW-MAJOR pool: exactly when dispatch would pick the gather
        kernels for its pair groups (the resident kernel predicate says
        no) and the row-major kernels can buffer the widest group's
        operand rows.  Multi-fold groups always gather, so parts without
        pair groups prefer row-major whenever the buffer bound allows."""
        from pilosa_tpu.ops.pallas_kernels import resident_strategy

        return not resident_strategy(n_rows, words, n_pairs) and self.rowmajor_ok(
            n_slices, words, max_k
        )

    def gather_count_rowmajor_dev(self, op: str, row_major, pairs):
        return self._dispatch.gather_count_rowmajor(
            op, self._jnp.asarray(row_major), self._jnp.asarray(pairs)
        )

    def gather_count_multi_rowmajor_dev(self, op: str, row_major, idx):
        return self._dispatch.gather_count_multi_rowmajor(
            op, self._jnp.asarray(row_major), self._jnp.asarray(idx)
        )

    def grow_rows_rm(self, matrix, n: int):
        """Append n zero SLOTS to a row-major [cap, S, ...] pool matrix."""
        z = self._jnp.zeros((n,) + matrix.shape[1:], dtype=matrix.dtype)
        return self._jnp.concatenate([matrix, z], axis=0)

    def set_rows_at_rm(self, matrix, slots, block):
        """Scatter a row-major miss batch [k, S, W] into slots (axis 0)."""
        idx = self._jnp.asarray(np.asarray(slots, dtype=np.int32))
        return matrix.at[idx].set(self._match_block(matrix, block))

    def set_plane_rows_rm(self, matrix, slice_idxs, slots, block):
        """Refresh (slot, stale-slice) cells of a row-major matrix;
        block: [len(slots), len(slice_idxs), W]."""
        sl = self._jnp.asarray(np.asarray(slots, dtype=np.int32))
        si = self._jnp.asarray(np.asarray(slice_idxs, dtype=np.int32))
        return matrix.at[sl[:, None], si[None, :]].set(
            self._match_block(matrix, block)
        )

    def gather_count_multi_dev(self, op: str, row_matrix, idx):
        return self._dispatch.gather_count_multi(
            op, self._jnp.asarray(row_matrix), self._jnp.asarray(idx)
        )

    # -- TopN all-slice candidate scorer (one dispatch per chunk set) ----

    @property
    def row_scorer_all_slices(self) -> bool:
        """Single-chip jax engines route through the memoizing scorer
        factory too (round 5): phase-1 candidate chunks dispatch their
        one slice eagerly, and a candidate set re-asked by a SECOND
        slice (phase 2's merged-id refetch) upgrades to one all-slice
        launch memoized for the rest."""
        return True

    @property
    def supports_single_slice_score(self) -> bool:
        """Whether ``matrix[si]`` indexing is process-addressable (true
        off-mesh; multi-process meshes must stay SPMD)."""
        return True

    def prepare_topn_src(self, src_stack: np.ndarray):
        """Upload a host [S, W] src stack once per TopN query (tiled)."""
        src = np.ascontiguousarray(src_stack)
        self.stat_upload_bytes += src.nbytes
        return self._jnp.asarray(self._tile_host(src))

    def topn_scorer_counts(self, matrix, pos, src_dev) -> np.ndarray:
        """int32[S, K] candidate counts in one dispatch (fused Pallas
        kernel on TPU; per-slice jnp fallback elsewhere)."""
        out = self._dispatch.topn_scorer_counts(
            self._jnp.asarray(matrix),
            self._jnp.asarray(np.asarray(pos, dtype=np.int32)),
            src_dev,
        )
        return self.to_numpy(out).astype(np.int64)

    def gather_count_tree(self, row_matrix, leaves, opc) -> np.ndarray:
        return self.to_numpy(
            self.gather_count_tree_dev(row_matrix, leaves, opc)
        ).astype(np.int64)

    def gather_count_tree_dev(self, row_matrix, leaves, opc):
        return self._dispatch.gather_count_tree(
            self._jnp.asarray(row_matrix),
            self._jnp.asarray(leaves),
            self._jnp.asarray(opc),
        )

    def bit_and(self, a, b):
        return self._jnp.bitwise_and(a, b)

    def bit_or(self, a, b):
        return self._jnp.bitwise_or(a, b)

    def bit_xor(self, a, b):
        return self._jnp.bitwise_xor(a, b)

    def bit_andnot(self, a, b):
        return self._jnp.bitwise_and(a, self._jnp.bitwise_not(b))

    def zeros_like(self, a):
        return self._jnp.zeros_like(a)

    def count(self, batch) -> np.ndarray:
        if batch.size == 0:
            return np.zeros(batch.shape[:-1], dtype=np.int64)
        return self.to_numpy(self._dispatch.count(batch)).astype(np.int64)

    def batch_intersection_count(self, rows, src, tiled: bool = False) -> np.ndarray:
        # ``tiled=True``: rows were sliced from a (4D tiled) engine matrix
        # and carry the word axis as trailing [W/128, 128] dims.  Explicit
        # — ndim alone cannot distinguish a tiled [K, W/128, 128] stack
        # from a logical [S, K, W] one.
        return self.to_numpy(
            self._dispatch.batch_intersection_count(rows, src, tiled=tiled)
        ).astype(np.int64)

    def tile_src(self, src_dense: np.ndarray):
        """Upload a dense [W] operand in the matrix-compatible tiled form
        (so kernels can pair it with rows sliced from a 4D matrix)."""
        src = np.asarray(src_dense)
        self.stat_upload_bytes += src.nbytes
        return self._jnp.asarray(self._tile_host(src))

    def _match_block(self, matrix, block):
        """Reshape a host [.., .., W] block to the matrix's storage form
        (tiled 4D matrices take [.., .., W/128, 128] blocks)."""
        block = np.asarray(block)
        self.stat_upload_bytes += block.nbytes
        if matrix.ndim == block.ndim + 1:
            block = self._tile_host(block)
        return self._jnp.asarray(block)

    def update_slices(self, matrix, slice_idxs, planes):
        """Replace stale slice planes on-device: uploads only the changed
        planes and patches HBM→HBM instead of re-transferring the matrix."""
        idx = self._jnp.asarray(np.asarray(slice_idxs, dtype=np.int32))
        return matrix.at[idx].set(self._match_block(matrix, planes))

    def append_rows(self, matrix, block):
        """Device-side concat of new rows: only the new block crosses PCIe."""
        return self._jnp.concatenate(
            [matrix, self._match_block(matrix, block)], axis=1
        )

    def set_rows(self, matrix, row_start: int, block):
        """Write rows into preallocated capacity device-side (shape
        preserved, so downstream jitted kernels never recompile)."""
        return matrix.at[:, row_start : row_start + block.shape[1]].set(
            self._match_block(matrix, block)
        )

    def set_rows_at(self, matrix, slots, block):
        """Scatter a miss batch into arbitrary pool slots: only the new
        rows cross host->device; the scatter itself is HBM->HBM."""
        idx = self._jnp.asarray(np.asarray(slots, dtype=np.int32))
        return matrix.at[:, idx].set(self._match_block(matrix, block))

    def grow_rows(self, matrix, n: int):
        """Append n zero capacity rows DEVICE-side (no host transfer)."""
        s = matrix.shape[0]
        z = self._jnp.zeros((s, n) + matrix.shape[2:], dtype=matrix.dtype)
        return self._jnp.concatenate([matrix, z], axis=1)

    def set_plane_rows(self, matrix, slice_idxs, slots, block):
        """Scatter (stale slice, resident slot) cells: only the touched
        rows cross host->device."""
        si = self._jnp.asarray(np.asarray(slice_idxs, dtype=np.int32))
        sl = self._jnp.asarray(np.asarray(slots, dtype=np.int32))
        return matrix.at[si[:, None], sl[None, :]].set(
            self._match_block(matrix, block)
        )

    def build_planes(self, rows, cols):
        """Bulk sort/segment/scatter build on device: the jitted pack
        kernel sorts, dedups, and scatters the bit columns under jax.jit
        on padded power-of-two shapes (see bulk/build.py); the group
        table computes on host, where the fragment commit needs it."""
        from pilosa_tpu.bulk.build import build_planes_jax

        return build_planes_jax(rows, cols, jnp=self._jnp)

    def pair_gram(self, matrix):
        """All-pairs AND-count Gram via one MXU int8 matmul (exact)."""
        if not hasattr(self, "_gram_jit"):
            import jax

            from pilosa_tpu.ops.bitwise import pair_gram

            self._gram_jit = jax.jit(pair_gram)
        return self.to_numpy(self._gram_jit(self._jnp.asarray(matrix))).astype(np.int64)

    def gram_update_rows(self, matrix, gram, slots, old_matrix=None, slice_idxs=None):
        """Rank-k Gram repair (see NumpyEngine.gram_update_rows): one
        batched gather-count dispatch recomputes the dirty rows/columns.
        The dirty-slot axis pads to a power-of-two bucket (recomputing a
        row twice is idempotent) so the jitted dispatch shape stays
        stable across repairs of 1..K rows.

        Per-(row, slice) delta mode (old_matrix + slice_idxs): two
        dispatches restricted to the written slice planes adjust the
        dirty rows by (new - old) — unchanged slices cancel, so a
        single-slice write repairs in O(K*R) counts regardless of the
        state's span.  The restricted slice axis pads to a power-of-two
        bucket with a CLEAN (unwritten) slice so jitted shapes stay
        stable: a clean slice's old and new planes are identical, so its
        padded contribution cancels exactly.  Falls back to the full
        recompute when no clean pad slice exists or the restriction
        wouldn't pay (>= half the slices dirty after padding)."""
        slots = sorted({int(s) for s in slots})
        k = len(slots)
        kb = 1 << (k - 1).bit_length() if k > 1 else 1
        padded = np.asarray(slots + [slots[0]] * (kb - k), dtype=np.int32)
        n = gram.shape[0]
        pairs = np.empty((kb * n, 2), dtype=np.int32)
        pairs[:, 0] = np.repeat(padded, n)
        pairs[:, 1] = np.tile(np.arange(n, dtype=np.int32), kb)
        idx = np.asarray(slots, dtype=np.int64)
        n_slices = matrix.shape[0]
        si = sorted({int(s) for s in slice_idxs}) if slice_idxs is not None else None
        if old_matrix is not None and si:
            sb = 1 << (len(si) - 1).bit_length() if len(si) > 1 else 1
            clean = next((s for s in range(n_slices) if s not in set(si)), None)
            if clean is not None and 2 * sb < n_slices:
                sel = self._jnp.asarray(
                    np.asarray(si + [clean] * (sb - len(si)), dtype=np.int32)
                )
                if 2 * k >= n:
                    # Wide repairs (a coalesced burst dirtying most of the
                    # matrix): k*R direct pair counts approach the cost of
                    # the whole Gram — two restricted-slice pair_gram
                    # builds (MXU matmul shape; fixed R^2 cost) beat the
                    # gather dispatch past k ~ R/2 (measured on the CPU
                    # build host; the MXU makes them cheaper still), and
                    # the FULL-gram delta is exact (pairs with no dirty
                    # row have identical planes in old and new, so their
                    # delta is zero).
                    pg_new = self.pair_gram(matrix[sel])
                    pg_old = None if pg_new is None else self.pair_gram(old_matrix[sel])
                    if pg_old is not None:
                        return (
                            np.asarray(gram) + (pg_new - pg_old)
                        ).astype(gram.dtype)
                new_c = np.asarray(self.gather_count("and", matrix[sel], pairs))
                old_c = np.asarray(self.gather_count("and", old_matrix[sel], pairs))
                delta = (new_c.astype(np.int64) - old_c.astype(np.int64)).reshape(
                    kb, n
                )[:k]
                block = (np.asarray(gram)[idx, :] + delta).astype(gram.dtype)
                out = np.array(gram, copy=True)
                out[idx, :] = block
                out[:, idx] = block.T
                return out
        block = (
            np.asarray(self.gather_count("and", matrix, pairs))
            .reshape(kb, n)[:k]
            .astype(gram.dtype)
        )
        out = np.array(gram, copy=True)
        out[idx, :] = block
        out[:, idx] = block.T
        return out

    def to_numpy(self, x) -> np.ndarray:
        return np.asarray(x)


class MeshEngine(JaxEngine):
    """JaxEngine whose slice stacks are sharded over a local device mesh.

    The executor's local map phase becomes a single GSPMD computation: the
    leading (slice) axis of every stack is partitioned over the
    ``SliceMesh`` (parallel/sharded.py), elementwise set ops stay
    shard-local, and reductions (Count, TopN candidate counts) get their
    cross-device psum/all-gather inserted by XLA from the shardings — the
    in-process analog of the reference's goroutine-per-slice fan-out
    (executor.go:1209-1244), with ICI replacing channels.

    Falls back to replication for stacks whose leading axis can't shard
    (empty or single-slice).
    """

    name = "mesh"

    # Mesh matrices shard the SLICE axis; a row-major layout would shard
    # rows instead — keep streaming transients slice-major on meshes.
    supports_row_major_gather = False

    @property
    def supports_row_scorer(self) -> bool:
        """Always true: single-process meshes use the eager per-slice row
        indexing path; multi-process meshes route through the shard_map'd
        all-slice scorer (topn_scorer_counts + allgather) instead, since
        eagerly indexing ``matrix[si]`` requires every shard to be
        process-addressable."""
        return True

    @property
    def row_scorer_all_slices(self) -> bool:
        """Meshes always route through the hybrid scorer factory; the
        single-vs-all-slice dispatch decision lives there, gated by
        supports_single_slice_score (multi-process meshes must stay
        SPMD — eager matrix[si] indexing would touch non-addressable
        shards)."""
        return True

    @property
    def supports_single_slice_score(self) -> bool:
        """Multi-process meshes cannot index ``matrix[si]`` eagerly —
        shards live on other processes; single-process meshes can."""
        import jax

        return jax.process_count() == 1

    def prepare_topn_src(self, src_stack: np.ndarray):
        """Upload a host [S, W] src stack ONCE per TopN query (tiled +
        slice-sharded) for repeated topn_scorer_counts dispatches."""
        return self._shard_stack(self._tile_host(np.ascontiguousarray(src_stack)))

    def topn_scorer_counts(self, matrix, pos, src_dev) -> np.ndarray:
        """Per-(slice, candidate) |row & src| counts over the WHOLE mesh
        in one SPMD dispatch: int32[S, K] fetched (allgathered) to every
        rank.  src_dev: the prepare_topn_src result (device-resident —
        re-uploading ~S*128 KiB per candidate chunk would dominate)."""
        from pilosa_tpu.parallel.sharded import sharded_scorer_counts

        ids = self._jnp.asarray(np.asarray(pos, dtype=np.int32))
        out = sharded_scorer_counts(self.mesh, matrix, ids, src_dev)
        return self._fetch(out).astype(np.int64)

    def __init__(self, devices=None):
        super().__init__()
        from pilosa_tpu.parallel import SliceMesh
        from pilosa_tpu.ops import bitwise as _bw

        import jax

        self._jax = jax
        self.mesh = SliceMesh(devices)
        # One jitted callable per fused path — constructing jax.jit per
        # call would re-trace and miss the dispatch cache every time.
        self._gather_jit = jax.jit(_bw.gather_count, static_argnums=0)
        self._gather_multi_jit = jax.jit(_bw.gather_count_multi, static_argnums=0)
        self._tree_jit = None  # built on first tree batch

    def _shard_stack(self, x):
        # Shard only cleanly-divisible leading axes (device_put requires
        # even shards); ragged slice counts stay unsharded — correctness
        # first, placement when the shapes allow it.  Only stack_slices
        # routes here, so the leading axis is always the slice axis.
        if isinstance(x, np.ndarray):
            self.stat_upload_bytes += x.nbytes
        if x.ndim < 2 or x.shape[0] < 2 or x.shape[0] % self.mesh.n_devices:
            return self._jnp.asarray(x)
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(self.mesh.AXIS, *([None] * (x.ndim - 1)))
        return self._jax.device_put(x, NamedSharding(self.mesh.mesh, spec))

    def stack(self, rows: list):
        return self.stack_slices(rows)

    def stack_slices(self, stacks: list):
        return self._shard_stack(super().stack_rows(stacks))

    def matrix(self, host_matrix: np.ndarray):
        """One sharded transfer: the slice axis lands partitioned; stored
        in the same tiled 4D form as JaxEngine (relayout-free kernels)."""
        return self._shard_stack(self._tile_host(host_matrix))

    def _repin(self, out, like):
        # Scatter/concat along or around the sharded slice axis may leave
        # the result replicated; pin it back to the source's sharding.
        sharding = getattr(like, "sharding", None)
        if sharding is not None:
            out = self._jax.device_put(out, sharding)
        return out

    def update_slices(self, matrix, slice_idxs, planes):
        return self._repin(super().update_slices(matrix, slice_idxs, planes), matrix)

    def append_rows(self, matrix, block):
        return self._repin(super().append_rows(matrix, block), matrix)

    def set_rows(self, matrix, row_start, block):
        return self._repin(super().set_rows(matrix, row_start, block), matrix)

    def set_rows_at(self, matrix, slots, block):
        return self._repin(super().set_rows_at(matrix, slots, block), matrix)

    def grow_rows(self, matrix, n):
        return self._repin(super().grow_rows(matrix, n), matrix)

    def set_plane_rows(self, matrix, slice_idxs, slots, block):
        return self._repin(super().set_plane_rows(matrix, slice_idxs, slots, block), matrix)

    def gram_update_rows(self, matrix, gram, slots, old_matrix=None, slice_idxs=None):
        # No restricted-slice delta on meshes: indexing a subset of the
        # sharded slice axis breaks the shard_map divisibility the
        # kernels need (and touches non-addressable shards on
        # multi-process jobs).  The full rank-k recompute stays
        # SPMD-safe on every rank.
        return super().gram_update_rows(matrix, gram, slots)

    def _pallas_mode(self, n_slices: int, w: int) -> str:
        """How to run kernels under the mesh: "pallas" (shard_map'd
        hand-tuned kernels, TPU), "interpret" (same composition, Pallas
        interpret mode — CPU meshes under PILOSA_TPU_PALLAS_INTERPRET=1,
        used by tests and the driver dryrun), or "" (jnp fallback)."""
        from pilosa_tpu.ops.pallas_kernels import _tileable

        if n_slices < 2 or n_slices % self.mesh.n_devices or not _tileable(w):
            return ""
        from pilosa_tpu.ops.dispatch import use_pallas

        if use_pallas():
            return "pallas"
        # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
        if os.environ.get("PILOSA_TPU_PALLAS_INTERPRET", "").lower() in ("1", "true", "yes"):
            return "interpret"
        return ""

    def gather_count(self, op, row_matrix, pairs):
        # A pallas_call can't lower under GSPMD partitioning directly, but
        # shard_map restores the kernel tier: each shard runs the SAME
        # hand-tuned Pallas kernel on its local block and psum merges over
        # ICI (parallel/sharded.py).  Shapes the mesh can't shard evenly
        # (or non-TPU without interpret mode) keep the jnp form, which XLA
        # partitions itself.
        from pilosa_tpu.ops.pallas_kernels import rm_words

        rm = self._shard_stack(self._jnp.asarray(row_matrix))
        mode = self._pallas_mode(rm.shape[0], rm_words(rm))
        if mode:
            from pilosa_tpu.parallel.sharded import sharded_gather_count

            out = sharded_gather_count(
                self.mesh, op, rm, self._jnp.asarray(pairs),
                interpret=(mode == "interpret"),
            )
            return self._fetch(out).astype(np.int64)
        out = self._gather_jit(op, rm, self._jnp.asarray(pairs))
        return self._fetch(out).astype(np.int64)

    def _fetch(self, arr) -> np.ndarray:
        """Fetch an engine array to host, allgathering when its shards
        span other processes (multi-host mesh) — the DCN analog of the
        reference streaming result segments back to the coordinator."""
        if getattr(arr, "is_fully_addressable", True) or getattr(
            arr, "is_fully_replicated", False
        ):
            return np.asarray(arr)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))

    def to_numpy(self, x) -> np.ndarray:
        # Every inherited JaxEngine host conversion routes through here,
        # so allgather-aware fetching covers them all on multi-host.
        return self._fetch(x)

    def gather_count_multi(self, op, row_matrix, idx):
        from pilosa_tpu.ops.pallas_kernels import rm_words

        rm = self._shard_stack(self._jnp.asarray(row_matrix))
        s, w = rm.shape[0], rm_words(rm)
        k = idx.shape[1]
        mode = self._pallas_mode(s, w)
        if mode:
            # Kernel tier under the mesh (no materialized gather); bound
            # the prefetched id footprint like single-chip dispatch does.
            from pilosa_tpu.parallel.sharded import sharded_gather_count_multi

            chunk = max(1, 2048 // max(1, k))
            outs = [
                self._fetch(
                    sharded_gather_count_multi(
                        self.mesh, op, rm, self._jnp.asarray(idx[i : i + chunk]),
                        interpret=(mode == "interpret"),
                    )
                )
                for i in range(0, idx.shape[0], chunk)
            ]
            return np.concatenate(outs).astype(np.int64)
        # The jnp form materializes the [S, chunk, K, W] gather per shard;
        # chunk the batch so that transient stays bounded (the same budget
        # dispatch.py applies to its XLA fallback).
        from pilosa_tpu.pilosa import OR_MULTI_BUDGET_DEVICE, or_multi_chunk_size

        chunk = or_multi_chunk_size(s, k, w, OR_MULTI_BUDGET_DEVICE)
        outs = [
            self._fetch(self._gather_multi_jit(op, rm, self._jnp.asarray(idx[i : i + chunk])))
            for i in range(0, idx.shape[0], chunk)
        ]
        return np.concatenate(outs).astype(np.int64)

    def gather_count_or_multi(self, row_matrix, idx):
        return self.gather_count_multi("or", row_matrix, idx)

    def gather_count_tree(self, row_matrix, leaves, opc):
        from pilosa_tpu.ops.pallas_kernels import rm_words

        rm = self._shard_stack(self._jnp.asarray(row_matrix))
        s, w = rm.shape[0], rm_words(rm)
        k = leaves.shape[1]
        mode = self._pallas_mode(s, w)
        if mode:
            from pilosa_tpu.parallel.sharded import sharded_gather_count_tree

            return self._fetch(
                sharded_gather_count_tree(
                    self.mesh, rm, self._jnp.asarray(leaves),
                    self._jnp.asarray(opc), interpret=(mode == "interpret"),
                )
            ).astype(np.int64)
        # jnp form materializes the gather per shard: bound the transient
        # exactly like gather_count_multi's fallback.
        from pilosa_tpu.ops import bitwise as _bw
        from pilosa_tpu.pilosa import OR_MULTI_BUDGET_DEVICE, or_multi_chunk_size

        if self._tree_jit is None:
            self._tree_jit = self._jax.jit(_bw.gather_count_tree)
        chunk = or_multi_chunk_size(s, k, w, OR_MULTI_BUDGET_DEVICE)
        outs = [
            self._fetch(
                self._tree_jit(
                    rm, self._jnp.asarray(leaves[i : i + chunk]),
                    self._jnp.asarray(opc[i : i + chunk]),
                )
            )
            for i in range(0, leaves.shape[0], chunk)
        ]
        return np.concatenate(outs).astype(np.int64)

    def gather_count_dev(self, op, row_matrix, pairs):
        # Sharded matrices go through the GSPMD-partitioned jnp form (the
        # Pallas dispatch the Jax parent would pick can't lower under
        # GSPMD); the result is small, so the sync fetch costs little.
        return self.gather_count(op, row_matrix, pairs)

    def gather_count_multi_dev(self, op, row_matrix, idx):
        return self.gather_count_multi(op, row_matrix, idx)

    def gather_count_tree_dev(self, row_matrix, leaves, opc):
        return self.gather_count_tree(row_matrix, leaves, opc)


def new_engine(name: str = "auto"):
    """Engine factory. "auto" honors PILOSA_TPU_ENGINE, defaulting to jax
    with a numpy fallback when no jax backend can initialize."""
    fallback_ok = False
    if name == "auto":
        env = os.environ.get("PILOSA_TPU_ENGINE")
        # Only a true default (no env override) may silently fall back; an
        # explicit PILOSA_TPU_ENGINE=jax must surface jax failures.
        fallback_ok = env is None
        name = env or "jax"
    if name == "numpy":
        return NumpyEngine()
    if name == "mesh":
        return MeshEngine()
    if name == "jax":
        if fallback_ok:
            try:
                eng = JaxEngine()
                eng.count(eng.asarray(np.zeros(8, dtype=np.uint32)))  # backend probe
                return eng
            # analysis-ok: exception-hygiene: backend probe; the numpy engine is the documented fallback
            except Exception:
                return NumpyEngine()
        return JaxEngine()
    raise ValueError(f"unknown engine: {name!r}")
