import sys

from pilosa_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
