"""Prometheus text exposition for the expvar stats registry.

No reference analog — the reference exposes /debug/vars JSON only.  This
module renders everything an ExpvarStatsClient holds in the Prometheus
text format (``text/plain; version=0.0.4``), served at ``/metrics`` by
the server handler, the replica router, and the lockstep front end.

The metric-name mapping is MECHANICAL, so it can be checked statically:
every series name in the ``COUNTERS.md`` registry maps through
:func:`prom_name` — lowercase the expvar name, replace every character
outside ``[a-zA-Z0-9_]`` with ``_``, collapse runs, prefix ``pilosa_``,
and append ``_total`` for counters.  The stats-registry analysis rule
(``analysis/rules.py:rule_stats_registry``) runs the same mapping over
the registry and fails when a registered series would render an invalid
Prometheus name or two distinct series would collide after mangling —
the registry gate now covers the exposition, so ``/metrics`` and
``COUNTERS.md`` cannot drift silently.

Tag handling: the expvar client stores tagged series under
``name[tag1,tag2]`` keys with ``key:value`` tags (``index:foo``);
:func:`split_key` turns that suffix into Prometheus labels.  Histograms
and timings render as summaries (quantile samples from the bounded
reservoir plus exact ``_count``/``_sum``).  Sets render as a gauge ``1``
with the string value as a ``value`` label (Prometheus has no string
samples).

:func:`parse_exposition` is a strict parser/validator for the text
format — the bench preflight and the exposition tests scrape
``/metrics`` and fail on anything unparseable.
"""

from __future__ import annotations

import math
import re
from typing import Optional

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

PREFIX = "pilosa_"

_MANGLE_RX = re.compile(r"[^a-zA-Z0-9_]+")
_VALID_METRIC_RX = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_VALID_LABEL_RX = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# One sample line: name, optional {labels}, value, optional timestamp.
_SAMPLE_RX = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(?:\{([^}]*)\})?"                       # optional label set
    r"\s+(\S+)"                               # value
    r"(?:\s+(-?\d+))?$"                       # optional timestamp (ms)
)
_LABEL_RX = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def prom_name(name: str, kind: str = "") -> str:
    """The mechanical expvar-series -> Prometheus-metric-name mapping.

    ``qcache.hit`` -> ``pilosa_qcache_hit_total`` (counters get the
    conventional ``_total`` suffix); ``qos.latency_ms.read`` ->
    ``pilosa_qos_latency_ms_read``.  Registry placeholder segments like
    ``<cls>`` mangle to plain ``cls`` so registered patterns stay valid
    names for the drift gate."""
    base = _MANGLE_RX.sub("_", name.strip().lower()).strip("_")
    base = re.sub(r"__+", "_", base)
    out = PREFIX + base
    if kind == "counter":
        out += "_total"
    return out


def valid_metric_name(name: str) -> bool:
    return bool(_VALID_METRIC_RX.match(name))


def split_key(key: str) -> tuple[str, dict[str, str]]:
    """Split an expvar map key ``name[tag1,tag2]`` into (base name,
    labels).  Tags are ``key:value`` strings (``index:foo``); a bare tag
    with no colon becomes a ``tag`` label.  Duplicate label keys keep
    the last value (tags are sorted/deduped upstream)."""
    if not key.endswith("]"):
        return key, {}
    i = key.find("[")
    if i < 0:
        return key, {}
    base, raw = key[:i], key[i + 1 : -1]
    labels: dict[str, str] = {}
    for tag in raw.split(","):
        tag = tag.strip()
        if not tag:
            continue
        k, sep, v = tag.partition(":")
        if not sep:
            k, v = "tag", tag
        k = _MANGLE_RX.sub("_", k.strip().lower()).strip("_") or "tag"
        labels[k] = v.strip()
    return base, labels


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render(stats) -> str:
    """Render one stats client's full contents as Prometheus text.

    Accepts anything with ``snapshot_typed()`` (ExpvarStatsClient,
    MultiStatsClient wrapping one); a client without it (Nop) renders as
    an empty, still-valid exposition."""
    typed = stats.snapshot_typed() if hasattr(stats, "snapshot_typed") else {}
    if not typed:
        return ""
    # family name -> (type, [(labels, value), ...]); one # TYPE line per
    # family, samples grouped under it, families sorted for stable diffs.
    families: dict[str, tuple[str, list]] = {}

    def add(name: str, kind: str, labels: dict, value) -> None:
        fam = families.get(name)
        if fam is None:
            fam = families[name] = (kind, [])
        fam[1].append((labels, value))

    for key, value in typed.get("counters", {}).items():
        base, labels = split_key(key)
        add(prom_name(base, "counter"), "counter", labels, value)
    for key, value in typed.get("gauges", {}).items():
        base, labels = split_key(key)
        add(prom_name(base), "gauge", labels, value)
    for key, value in typed.get("sets", {}).items():
        base, labels = split_key(key)
        labels = dict(labels)
        labels["value"] = str(value)
        add(prom_name(base), "gauge", labels, 1)
    for key, h in typed.get("histograms", {}).items():
        base, labels = split_key(key)
        name = prom_name(base)
        for q, field in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            ql = dict(labels)
            ql["quantile"] = q
            add(name, "summary", ql, h[field])
        add(name + "_count", "summary.count", labels, h["count"])
        add(name + "_sum", "summary.sum", labels, h["sum"])
    for key, t in typed.get("timings", {}).items():
        base, labels = split_key(key)
        name = prom_name(base) + "_seconds"
        add(name + "_count", "summary.count", labels, t["count"])
        add(name + "_sum", "summary.sum", labels, t["sum"])

    lines: list[str] = []
    # _count/_sum samples belong to the summary family of their base
    # name; emit the TYPE line once for the base, then all its rows.
    emitted_types: set[str] = set()
    for name in sorted(families):
        kind, samples = families[name]
        if kind in ("counter", "gauge", "summary"):
            if name not in emitted_types:
                lines.append(f"# TYPE {name} {kind if kind != 'summary' else 'summary'}")
                emitted_types.add(name)
        elif kind in ("summary.count", "summary.sum"):
            base = name.rsplit("_", 1)[0]
            if base not in emitted_types and base not in families:
                # A timing family has no quantile rows; declare the
                # summary type on the base name before its _count/_sum.
                lines.append(f"# TYPE {base} summary")
                emitted_types.add(base)
        for labels, value in samples:
            lines.append(f"{name}{_fmt_labels(labels)} {_fmt_value(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def parse_exposition(text: str) -> dict[str, dict]:
    """Strict parse of a Prometheus text exposition.  Returns
    ``{family: {"type": t, "samples": n}}`` (the ``_count``/``_sum``
    rows of a summary count toward their base family).  Raises
    ``ValueError`` naming the offending line on anything malformed —
    the bench preflight's contract."""
    families: dict[str, dict] = {}

    def family_of(name: str) -> str:
        for suffix in ("_count", "_sum", "_bucket"):
            if name.endswith(suffix):
                base = name[: -len(suffix)]
                if base in families:
                    return base
        return name

    for ln, line in enumerate(text.splitlines(), 1):
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 2 or parts[1] not in ("TYPE", "HELP"):
                raise ValueError(f"line {ln}: malformed comment: {line!r}")
            if parts[1] == "TYPE":
                if len(parts) != 4:
                    raise ValueError(f"line {ln}: malformed TYPE line: {line!r}")
                _, _, name, kind = parts
                if not valid_metric_name(name):
                    raise ValueError(f"line {ln}: invalid metric name {name!r}")
                if kind not in ("counter", "gauge", "summary", "histogram", "untyped"):
                    raise ValueError(f"line {ln}: unknown metric type {kind!r}")
                if name in families:
                    raise ValueError(f"line {ln}: duplicate TYPE for {name!r}")
                families[name] = {"type": kind, "samples": 0}
            continue
        m = _SAMPLE_RX.match(line)
        if m is None:
            raise ValueError(f"line {ln}: malformed sample: {line!r}")
        name, raw_labels, raw_value = m.group(1), m.group(2), m.group(3)
        if raw_labels:
            # Sequential tokenize: label pairs separated by commas, full
            # consumption required (values may themselves hold spaces or
            # commas inside the quotes).
            pos = 0
            while pos < len(raw_labels):
                lm = _LABEL_RX.match(raw_labels, pos)
                if lm is None:
                    raise ValueError(
                        f"line {ln}: malformed labels: {raw_labels!r}"
                    )
                pos = lm.end()
                if pos < len(raw_labels):
                    if raw_labels[pos] != ",":
                        raise ValueError(
                            f"line {ln}: malformed labels: {raw_labels!r}"
                        )
                    pos += 1
        if raw_value not in ("NaN", "+Inf", "-Inf"):
            try:
                float(raw_value)
            except ValueError:
                raise ValueError(f"line {ln}: bad sample value {raw_value!r}")
        fam = family_of(name)
        rec = families.get(fam)
        if rec is None:
            rec = families[fam] = {"type": "untyped", "samples": 0}
        rec["samples"] += 1
    return families


def registry_collisions(names_by_kind: dict[str, str]) -> list[tuple[str, str, str]]:
    """The drift gate's core check: map every registry series through
    :func:`prom_name` and report (series_a, series_b, prom) triples
    where two DISTINCT registered series collide after mangling, plus
    (series, "", prom) entries whose mangled form is not a valid metric
    name.  ``names_by_kind`` maps registry series name -> kind
    ("counter"/"gauge"/"histogram"/"timing"/"set")."""
    out: list[tuple[str, str, str]] = []
    seen: dict[str, str] = {}
    for name in sorted(names_by_kind):
        kind = names_by_kind[name]
        p = prom_name(name, "counter" if kind == "counter" else "")
        base_empty = not _MANGLE_RX.sub("_", name.strip().lower()).strip("_")
        if not valid_metric_name(p) or base_empty:
            out.append((name, "", p))
            continue
        prev = seen.get(p)
        if prev is not None and prev != name:
            out.append((prev, name, p))
        else:
            seen[p] = name
    return out


def clamp_float(raw: Optional[str], default: float = 0.0, lo: float = 0.0,
                hi: float = float("inf")) -> float:
    """Parse a query-string float, clamping instead of raising: a
    malformed or out-of-range ``?min-ms=`` must not 400 a debug
    endpoint (satellite fix shared by the handler, router, and
    lockstep front end)."""
    try:
        v = float(raw) if raw is not None else default
    except (TypeError, ValueError):
        return default
    if math.isnan(v):
        return default
    return min(max(v, lo), hi)


def clamp_int(raw: Optional[str], default: int = 0, lo: int = 0,
              hi: int = 1 << 30) -> int:
    """Integer twin of :func:`clamp_float` for ``?limit=``."""
    try:
        v = int(float(raw)) if raw is not None else default
    except (TypeError, ValueError):
        return default
    return min(max(v, lo), hi)
