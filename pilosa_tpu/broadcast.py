"""Cluster broadcast: typed schema-mutation messages + transports.

Reference analog: broadcast.go (envelope: 1-byte type prefix + protobuf,
broadcast.go:110-166), httpbroadcast/ (HTTP POST to every node's internal
host), gossip/ (memberlist).  This build ships:

- the same typed envelope (type bytes 1-5, wire-compatible payloads),
- ``StaticNodeSet`` — fixed host list, no messaging (cluster type
  "static"),
- ``HTTPBroadcaster``/``HTTPBroadcastReceiver`` — sync fan-out over the
  internal HTTP port (cluster type "http").

The SWIM gossip transport (cluster type "gossip") lives in
``pilosa_tpu.gossip.GossipNodeSet``.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from pilosa_tpu import wire
from pilosa_tpu.wire import Writer, iter_fields

MESSAGE_TYPE_CREATE_SLICE = 1
MESSAGE_TYPE_CREATE_INDEX = 2
MESSAGE_TYPE_DELETE_INDEX = 3
MESSAGE_TYPE_CREATE_FRAME = 4
MESSAGE_TYPE_DELETE_FRAME = 5


# -- message payloads (private.proto) ---------------------------------------

def encode_create_slice(index: str, slice_i: int, is_inverse: bool = False) -> bytes:
    body = Writer().string(1, index).varint(2, slice_i).bool(3, is_inverse).finish()
    return bytes([MESSAGE_TYPE_CREATE_SLICE]) + body


def encode_create_index(index: str, column_label: str = "", time_quantum: str = "") -> bytes:
    meta = wire.encode_index_meta(column_label, time_quantum)
    body = Writer().string(1, index).message(2, meta).finish()
    return bytes([MESSAGE_TYPE_CREATE_INDEX]) + body


def encode_delete_index(index: str) -> bytes:
    return bytes([MESSAGE_TYPE_DELETE_INDEX]) + Writer().string(1, index).finish()


def encode_create_frame(index: str, frame: str, meta: dict) -> bytes:
    meta_raw = wire.encode_frame_meta(
        meta.get("rowLabel", ""),
        meta.get("inverseEnabled", False),
        meta.get("cacheType", ""),
        meta.get("cacheSize", 0),
        meta.get("timeQuantum", ""),
    )
    body = Writer().string(1, index).string(2, frame).message(3, meta_raw).finish()
    return bytes([MESSAGE_TYPE_CREATE_FRAME]) + body


def encode_delete_frame(index: str, frame: str) -> bytes:
    return bytes([MESSAGE_TYPE_DELETE_FRAME]) + Writer().string(1, index).string(2, frame).finish()


def decode_message(data: bytes) -> tuple[int, dict]:
    """(type, payload dict) — raises on unknown types (broadcast.go:142-166)."""
    if not data:
        raise ValueError("empty broadcast message")
    typ, body = data[0], data[1:]
    out: dict = {}
    if typ == MESSAGE_TYPE_CREATE_SLICE:
        for f, w, v in iter_fields(body):
            if f == 1:
                out["index"] = v.decode()
            elif f == 2:
                out["slice"] = v
            elif f == 3:
                out["isInverse"] = bool(v)
    elif typ in (MESSAGE_TYPE_CREATE_INDEX, MESSAGE_TYPE_DELETE_INDEX):
        for f, w, v in iter_fields(body):
            if f == 1:
                out["index"] = v.decode()
            elif f == 2 and typ == MESSAGE_TYPE_CREATE_INDEX:
                out["meta"] = wire.decode_index_meta(v)
    elif typ in (MESSAGE_TYPE_CREATE_FRAME, MESSAGE_TYPE_DELETE_FRAME):
        for f, w, v in iter_fields(body):
            if f == 1:
                out["index"] = v.decode()
            elif f == 2:
                out["frame"] = v.decode()
            elif f == 3 and typ == MESSAGE_TYPE_CREATE_FRAME:
                out["meta"] = wire.decode_frame_meta(v)
    else:
        raise ValueError(f"invalid message type: {typ}")
    return typ, out


# -- transports -------------------------------------------------------------


class NopBroadcaster:
    """broadcast.go NopBroadcaster."""

    def send_sync(self, msg: bytes) -> None:
        pass

    def send_async(self, msg: bytes) -> None:
        pass


class StaticNodeSet:
    """Fixed membership, no messaging (server/server.go 'static' type)."""

    def __init__(self, hosts: list[str]):
        self._hosts = list(hosts)

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    def nodes(self) -> list[str]:
        return list(self._hosts)


class HTTPBroadcaster:
    """POST the envelope to every peer's internal endpoint
    (httpbroadcast/messenger.go:45-121)."""

    def __init__(self, internal_hosts: list[str], self_host: str = "",
                 timeout: float = 10.0, stats=None):
        from pilosa_tpu.stats import NOP_STATS

        self.internal_hosts = list(internal_hosts)
        self.self_host = self_host
        self.timeout = timeout
        self.stats = stats if stats is not None else NOP_STATS
        self.stat_send_errors = 0

    def send_sync(self, msg: bytes) -> None:
        import urllib.request

        errs = []
        for host in self.internal_hosts:
            if host == self.self_host:
                continue
            url = host if "://" in host else f"http://{host}"
            req = urllib.request.Request(
                url + "/message", data=msg, method="POST",
                headers={"Content-Type": "application/octet-stream"},
            )
            try:
                urllib.request.urlopen(req, timeout=self.timeout).read()
            except Exception as e:
                errs.append(e)
        if errs:
            raise errs[0]

    def send_async(self, msg: bytes) -> None:
        threading.Thread(target=lambda: self._quiet_sync(msg), daemon=True).start()

    def _quiet_sync(self, msg: bytes) -> None:
        try:
            self.send_sync(msg)
        except Exception:
            # Async delivery is best-effort by contract; the drop is
            # counted so a steadily failing peer shows on a dashboard.
            self.stat_send_errors += 1
            self.stats.count("broadcast.send_errors")


class HTTPBroadcastReceiver:
    """Internal-port listener feeding a handler's receive_message
    (httpbroadcast/messenger.go:139-174)."""

    def __init__(self, port: int, handler: Optional[Callable[[bytes], None]] = None):
        self.port = port
        self.handler = handler
        self._server = None

    def start(self, handler: Callable[[bytes], None]) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        receiver = self

        class _MsgHandler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length)
                try:
                    handler(body)
                    code, payload = 200, b"{}"
                except Exception as e:
                    # error returns to the sender as the HTTP answer
                    code, payload = 400, str(e).encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, fmt, *args):
                pass

        self._server = ThreadingHTTPServer(("", self.port), _MsgHandler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


class SchemaBroadcaster:
    """High-level schema mutation broadcaster used by the HTTP handler.

    Wraps a transport broadcaster; called on local schema changes so peers
    apply the same mutation (server.go:259-304 ReceiveMessage loop is the
    other half, in pilosa_tpu.server.server).
    """

    def __init__(self, transport):
        self.transport = transport

    def create_index(self, index: str, options: dict) -> None:
        self.transport.send_sync(
            encode_create_index(index, options.get("columnLabel", ""), options.get("timeQuantum", ""))
        )

    def delete_index(self, index: str) -> None:
        self.transport.send_sync(encode_delete_index(index))

    def create_frame(self, index: str, frame: str, options: dict) -> None:
        self.transport.send_sync(encode_create_frame(index, frame, options))

    def delete_frame(self, index: str, frame: str) -> None:
        self.transport.send_sync(encode_delete_frame(index, frame))

    def create_slice(self, index: str, slice_i: int, is_inverse: bool = False) -> None:
        self.transport.send_async(encode_create_slice(index, slice_i, is_inverse))
