"""pilosa_tpu — a TPU-native distributed bitmap index.

A from-scratch rebuild of the capabilities of Pilosa (a distributed bitmap
index, reference: bussiere/pilosa) designed TPU-first:

- The hot path (bitwise set algebra + popcount, reference
  ``roaring/assembly_amd64.s``) runs as fused XLA/Pallas kernels over dense
  packed ``uint32`` bitmap arrays in HBM (`pilosa_tpu.ops`).
- The per-slice scatter/gather query execution (reference ``executor.go``
  mapReduce) becomes a single batched/sharded computation over a slice axis
  with XLA collectives (`pilosa_tpu.parallel`).
- Host-side storage keeps the roaring container format (array/bitmap
  containers, cookie-12346 serialization) at the storage/serialization
  boundary only (`pilosa_tpu.roaring`); on device everything is dense.

Layer map (mirrors SURVEY.md §1):

=====  =======================  =========================================
Layer  Module                   Reference analog
=====  =======================  =========================================
L0/L1  ops/, roaring.py         roaring/ + assembly_amd64.s
L2     core/fragment.py         fragment.go
L3     core/{holder,index,      holder.go, index.go, frame.go, view.go
       frame,view}.py
L4     executor.py, pql/        executor.go, pql/
L5     parallel/, cluster.py    cluster.go, broadcast.go, gossip/
L6     server/handler.py        handler.go, client.go, internal/
L7     server/server.py         server.go, server/server.go
L8     cli/                     cmd/, ctl/
=====  =======================  =========================================
"""

__version__ = "0.1.0"

from pilosa_tpu.pilosa import (  # noqa: F401
    PilosaError,
    ErrIndexExists,
    ErrIndexNotFound,
    ErrFrameExists,
    ErrFrameNotFound,
    ErrFragmentNotFound,
    ErrQueryRequired,
    validate_name,
    validate_label,
)

# Lazy top-level API (PEP 562): `pilosa_tpu.Holder` etc. without paying the
# jax import at package-import time (the numpy engine must work on hosts
# where jax is absent entirely).
_LAZY = {
    "Holder": ("pilosa_tpu.core.holder", "Holder"),
    "Index": ("pilosa_tpu.core.index", "Index"),
    "Frame": ("pilosa_tpu.core.frame", "Frame"),
    "FrameOptions": ("pilosa_tpu.core.frame", "FrameOptions"),
    "IndexOptions": ("pilosa_tpu.core.index", "IndexOptions"),
    "Executor": ("pilosa_tpu.executor", "Executor"),
    "Server": ("pilosa_tpu.server.server", "Server"),
    "Client": ("pilosa_tpu.server.client", "Client"),
    "Config": ("pilosa_tpu.config", "Config"),
    "LockstepService": ("pilosa_tpu.parallel.service", "LockstepService"),
}


__all__ = [
    "PilosaError", "ErrIndexExists", "ErrIndexNotFound", "ErrFrameExists",
    "ErrFrameNotFound", "ErrFragmentNotFound", "ErrQueryRequired",
    "validate_name", "validate_label", *sorted(_LAZY),
]


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    obj = getattr(importlib.import_module(target[0]), target[1])
    globals()[name] = obj  # cache: later accesses are plain dict hits
    return obj


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY)))
