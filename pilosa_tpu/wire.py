"""Protobuf wire codec + message types for the HTTP data plane.

Wire-compatible with the reference's protobuf schema (internal/public.proto
and internal/private.proto): field numbers, types, and the proto3 encoding
rules below are interface facts taken from those definitions; the runtime
is written from scratch (a ~200-line varint/length-delimited codec) rather
than generated, so this build carries no protobuf library dependency.

proto3 rules implemented: varint (wire type 0) for ints/bools with zero
values omitted, 64-bit (wire type 1) for double, length-delimited (wire
type 2) for strings/bytes/sub-messages/packed repeated scalars; unpacked
repeated scalar fields are also accepted on decode for compatibility.
"""

from __future__ import annotations

import struct
from typing import Any, Iterable

# Attr.Type enum (reference attr.go:36-39).
ATTR_TYPE_STRING = 1
ATTR_TYPE_INT = 2
ATTR_TYPE_BOOL = 3
ATTR_TYPE_FLOAT = 4


# ---------------------------------------------------------------------------
# Primitive codec
# ---------------------------------------------------------------------------

def encode_varint(v: int) -> bytes:
    if v < 0:
        v &= (1 << 64) - 1  # two's-complement for int64 fields
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(data: bytes, i: int) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        if i >= len(data):
            raise ValueError("truncated varint")
        b = data[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _tag(field: int, wire: int) -> bytes:
    return encode_varint((field << 3) | wire)


class Writer:
    def __init__(self):
        self.parts: list[bytes] = []

    def varint(self, field: int, v: int, *, force: bool = False) -> "Writer":
        if v or force:
            self.parts.append(_tag(field, 0))
            self.parts.append(encode_varint(int(v)))
        return self

    def bool(self, field: int, v: bool) -> "Writer":
        return self.varint(field, 1 if v else 0)

    def double(self, field: int, v: float) -> "Writer":
        if v != 0.0:
            self.parts.append(_tag(field, 1))
            self.parts.append(struct.pack("<d", v))
        return self

    def string(self, field: int, v: str) -> "Writer":
        if v:
            raw = v.encode()
            self.parts.append(_tag(field, 2))
            self.parts.append(encode_varint(len(raw)))
            self.parts.append(raw)
        return self

    def bytes_field(self, field: int, raw: bytes, *, force: bool = False) -> "Writer":
        if raw or force:
            self.parts.append(_tag(field, 2))
            self.parts.append(encode_varint(len(raw)))
            self.parts.append(raw)
        return self

    def message(self, field: int, msg: bytes) -> "Writer":
        return self.bytes_field(field, msg, force=True)

    def packed(self, field: int, values: Iterable[int]) -> "Writer":
        values = list(values)
        if values:
            from pilosa_tpu import native

            self.bytes_field(field, native.varint_encode(values), force=True)
        return self

    def finish(self) -> bytes:
        return b"".join(self.parts)


def iter_fields(data: bytes):
    """Yield (field_number, wire_type, value) triples."""
    i = 0
    n = len(data)
    while i < n:
        key, i = decode_varint(data, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = decode_varint(data, i)
            yield field, wire, v
        elif wire == 1:
            if i + 8 > n:
                raise ValueError("truncated fixed64")
            yield field, wire, struct.unpack_from("<d", data, i)[0]
            i += 8
        elif wire == 2:
            ln, i = decode_varint(data, i)
            if i + ln > n:
                raise ValueError("truncated length-delimited field")
            yield field, wire, data[i : i + ln]
            i += ln
        elif wire == 5:
            if i + 4 > n:
                raise ValueError("truncated fixed32")
            yield field, wire, struct.unpack_from("<f", data, i)[0]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")


def decode_packed_uint64(raw) -> list[int]:
    if isinstance(raw, int):  # unpacked single value
        return [raw]
    from pilosa_tpu import native

    return [int(v) for v in native.varint_decode(bytes(raw))]


# ---------------------------------------------------------------------------
# Attr maps (public.proto Attr/AttrMap; encode rules attr.go:303-363)
# ---------------------------------------------------------------------------

def encode_attr(key: str, value: Any) -> bytes:
    w = Writer().string(1, key)
    if isinstance(value, bool):
        w.varint(2, ATTR_TYPE_BOOL).bool(5, value)
    elif isinstance(value, str):
        w.varint(2, ATTR_TYPE_STRING).string(3, value)
    elif isinstance(value, int):
        w.varint(2, ATTR_TYPE_INT).varint(4, value)
    elif isinstance(value, float):
        w.varint(2, ATTR_TYPE_FLOAT).double(6, value)
    else:
        raise TypeError(f"unsupported attr type: {key}={value!r}")
    return w.finish()


def decode_attr(data: bytes) -> tuple[str, Any]:
    key, typ = "", 0
    sval, ival, bval, fval = "", 0, False, 0.0
    for field, wire, v in iter_fields(data):
        if field == 1:
            key = v.decode()
        elif field == 2:
            typ = v
        elif field == 3:
            sval = v.decode()
        elif field == 4:
            ival = _signed64(v)
        elif field == 5:
            bval = bool(v)
        elif field == 6:
            fval = v
    if typ == ATTR_TYPE_STRING:
        return key, sval
    if typ == ATTR_TYPE_INT:
        return key, ival
    if typ == ATTR_TYPE_BOOL:
        return key, bval
    if typ == ATTR_TYPE_FLOAT:
        return key, fval
    return key, None


def encode_attrs(attrs: dict) -> list[bytes]:
    return [encode_attr(k, attrs[k]) for k in sorted(attrs)]


def decode_attrs(raws: list[bytes]) -> dict:
    out = {}
    for raw in raws:
        k, v = decode_attr(raw)
        if k and v is not None:
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# Public messages (public.proto)
# ---------------------------------------------------------------------------

def encode_bitmap(bits: list[int], attrs: dict | None = None) -> bytes:
    w = Writer().packed(1, bits)
    for a in encode_attrs(attrs or {}):
        w.message(2, a)
    return w.finish()


def decode_bitmap(data: bytes) -> tuple[list[int], dict]:
    bits: list[int] = []
    attrs: list[bytes] = []
    for field, wire, v in iter_fields(data):
        if field == 1:
            bits.extend(decode_packed_uint64(v))
        elif field == 2:
            attrs.append(v)
    return bits, decode_attrs(attrs)


def encode_pair(id: int, count: int) -> bytes:
    return Writer().varint(1, id).varint(2, count).finish()


def decode_pair(data: bytes) -> tuple[int, int]:
    key = count = 0
    for field, wire, v in iter_fields(data):
        if field == 1:
            key = v
        elif field == 2:
            count = v
    return key, count


def encode_query_request(
    query: str,
    slices: list[int] | None = None,
    column_attrs: bool = False,
    quantum: str = "",
    remote: bool = False,
) -> bytes:
    return (
        Writer()
        .string(1, query)
        .packed(2, slices or [])
        .bool(3, column_attrs)
        .string(4, quantum)
        .bool(5, remote)
        .finish()
    )


def decode_query_request(data: bytes) -> dict:
    out = {"query": "", "slices": [], "column_attrs": False, "quantum": "", "remote": False}
    for field, wire, v in iter_fields(data):
        if field == 1:
            out["query"] = v.decode()
        elif field == 2:
            out["slices"].extend(decode_packed_uint64(v))
        elif field == 3:
            out["column_attrs"] = bool(v)
        elif field == 4:
            out["quantum"] = v.decode()
        elif field == 5:
            out["remote"] = bool(v)
    return out


def encode_query_result(result: Any) -> bytes:
    """Encode one executor result into a QueryResult message."""
    from pilosa_tpu.core.cache import Pair
    from pilosa_tpu.executor import QueryBitmap

    w = Writer()
    if isinstance(result, QueryBitmap):
        w.message(1, encode_bitmap(result.bits(), result.attrs))
    elif isinstance(result, bool):
        w.bool(4, result)
    elif isinstance(result, int):
        w.varint(2, result)
    elif isinstance(result, list):  # TopN pairs
        for p in result:
            if isinstance(p, Pair):
                w.message(3, encode_pair(p.id, p.count))
            else:
                w.message(3, encode_pair(p["id"], p["count"]))
    elif result is None:
        pass
    else:
        raise TypeError(f"cannot encode query result: {result!r}")
    return w.finish()


def decode_query_result(data: bytes) -> dict:
    out: dict[str, Any] = {}
    pairs = []
    for field, wire, v in iter_fields(data):
        if field == 1:
            bits, attrs = decode_bitmap(v)
            out["bitmap"] = {"bits": bits, "attrs": attrs}
        elif field == 2:
            out["n"] = v
        elif field == 3:
            pairs.append(decode_pair(v))
        elif field == 4:
            out["changed"] = bool(v)
    if pairs:
        out["pairs"] = [{"id": k, "count": c} for k, c in pairs]
    return out


def encode_column_attr_set(id: int, attrs: dict) -> bytes:
    w = Writer().varint(1, id)
    for a in encode_attrs(attrs):
        w.message(2, a)
    return w.finish()


def decode_column_attr_set(data: bytes) -> tuple[int, dict]:
    id = 0
    attrs: list[bytes] = []
    for field, wire, v in iter_fields(data):
        if field == 1:
            id = v
        elif field == 2:
            attrs.append(v)
    return id, decode_attrs(attrs)


def encode_query_response(
    results: list[Any] | None = None,
    err: str = "",
    column_attr_sets: list[tuple[int, dict]] | None = None,
) -> bytes:
    w = Writer().string(1, err)
    for r in results or []:
        w.message(2, encode_query_result(r))
    for id, attrs in column_attr_sets or []:
        w.message(3, encode_column_attr_set(id, attrs))
    return w.finish()


def decode_query_response(data: bytes) -> dict:
    out: dict[str, Any] = {"err": "", "results": [], "columnAttrSets": []}
    for field, wire, v in iter_fields(data):
        if field == 1:
            out["err"] = v.decode()
        elif field == 2:
            out["results"].append(decode_query_result(v))
        elif field == 3:
            id, attrs = decode_column_attr_set(v)
            out["columnAttrSets"].append({"id": id, "attrs": attrs})
    return out


def encode_import_request(
    index: str,
    frame: str,
    slice_i: int,
    row_ids: list[int],
    column_ids: list[int],
    timestamps: list[int] | None = None,
) -> bytes:
    return (
        Writer()
        .string(1, index)
        .string(2, frame)
        .varint(3, slice_i)
        .packed(4, row_ids)
        .packed(5, column_ids)
        .packed(6, timestamps or [])
        .finish()
    )


def decode_import_request(data: bytes) -> dict:
    out = {"index": "", "frame": "", "slice": 0, "rowIDs": [], "columnIDs": [], "timestamps": []}
    for field, wire, v in iter_fields(data):
        if field == 1:
            out["index"] = v.decode()
        elif field == 2:
            out["frame"] = v.decode()
        elif field == 3:
            out["slice"] = v
        elif field == 4:
            out["rowIDs"].extend(decode_packed_uint64(v))
        elif field == 5:
            out["columnIDs"].extend(decode_packed_uint64(v))
        elif field == 6:
            out["timestamps"].extend(_signed64(x) for x in decode_packed_uint64(v))
    return out


# ---------------------------------------------------------------------------
# Private messages (private.proto) — block sync, schema/broadcast, status
# ---------------------------------------------------------------------------

def encode_bit(row_id: int, column_id: int, timestamp: int = 0) -> bytes:
    """internal.Bit (public.proto:17-21)."""
    return Writer().varint(1, row_id).varint(2, column_id).varint(3, timestamp).finish()


def decode_bit(data: bytes) -> dict:
    out = {"rowID": 0, "columnID": 0, "timestamp": 0}
    for field, wire, v in iter_fields(data):
        if field == 1:
            out["rowID"] = v
        elif field == 2:
            out["columnID"] = v
        elif field == 3:
            out["timestamp"] = _signed64(v)
    return out


def encode_attr_map(attrs: dict) -> bytes:
    """internal.AttrMap (public.proto:34-36; the reference's attr-store
    value encoding, attr.go:303-363)."""
    w = Writer()
    for a in encode_attrs(attrs):
        w.message(1, a)
    return w.finish()


def decode_attr_map(data: bytes) -> dict:
    raws = [v for field, wire, v in iter_fields(data) if field == 1]
    return decode_attrs(raws)


def encode_import_response(err: str = "") -> bytes:
    """internal.ImportResponse (private.proto:17-19)."""
    return Writer().string(1, err).finish()


def decode_import_response(data: bytes) -> str:
    for field, wire, v in iter_fields(data):
        if field == 1:
            return v.decode()
    return ""


def encode_index_meta(column_label: str, time_quantum: str) -> bytes:
    return Writer().string(1, column_label).string(2, time_quantum).finish()


def decode_index_meta(data: bytes) -> dict:
    out = {"columnLabel": "", "timeQuantum": ""}
    for field, wire, v in iter_fields(data):
        if field == 1:
            out["columnLabel"] = v.decode()
        elif field == 2:
            out["timeQuantum"] = v.decode()
    return out


def encode_frame_meta(
    row_label: str, inverse_enabled: bool, cache_type: str, cache_size: int, time_quantum: str
) -> bytes:
    return (
        Writer()
        .string(1, row_label)
        .bool(2, inverse_enabled)
        .string(3, cache_type)
        .varint(4, cache_size)
        .string(5, time_quantum)
        .finish()
    )


def decode_frame_meta(data: bytes) -> dict:
    out = {"rowLabel": "", "inverseEnabled": False, "cacheType": "", "cacheSize": 0, "timeQuantum": ""}
    for field, wire, v in iter_fields(data):
        if field == 1:
            out["rowLabel"] = v.decode()
        elif field == 2:
            out["inverseEnabled"] = bool(v)
        elif field == 3:
            out["cacheType"] = v.decode()
        elif field == 4:
            out["cacheSize"] = v
        elif field == 5:
            out["timeQuantum"] = v.decode()
    return out


def encode_block_data_request(index: str, frame: str, view: str, slice_i: int, block: int) -> bytes:
    return (
        Writer()
        .string(1, index)
        .string(2, frame)
        .varint(3, block)
        .varint(4, slice_i)
        .string(5, view)
        .finish()
    )


def decode_block_data_request(data: bytes) -> dict:
    out = {"index": "", "frame": "", "view": "", "slice": 0, "block": 0}
    for field, wire, v in iter_fields(data):
        if field == 1:
            out["index"] = v.decode()
        elif field == 2:
            out["frame"] = v.decode()
        elif field == 3:
            out["block"] = v
        elif field == 4:
            out["slice"] = v
        elif field == 5:
            out["view"] = v.decode()
    return out


def encode_block_data_response(row_ids: list[int], column_ids: list[int]) -> bytes:
    return Writer().packed(1, row_ids).packed(2, column_ids).finish()


def decode_block_data_response(data: bytes) -> tuple[list[int], list[int]]:
    rows: list[int] = []
    cols: list[int] = []
    for field, wire, v in iter_fields(data):
        if field == 1:
            rows.extend(decode_packed_uint64(v))
        elif field == 2:
            cols.extend(decode_packed_uint64(v))
    return rows, cols


def encode_block_diff(
    set_rows: list[int], set_cols: list[int], clear_rows: list[int], clear_cols: list[int]
) -> bytes:
    """Internal sync message: bit diffs to apply to one fragment block.

    Not part of the reference wire surface — the reference pushes merge
    diffs as SetBit/ClearBit PQL (fragment.go:1403-1481), which re-derives
    view routing and labels on the peer; this message applies the diff to
    the exact (index, frame, view, slice) fragment instead, which is
    correct for inverse and time views too.
    """
    return (
        Writer()
        .packed(1, set_rows)
        .packed(2, set_cols)
        .packed(3, clear_rows)
        .packed(4, clear_cols)
        .finish()
    )


def decode_block_diff(data: bytes) -> tuple[list[int], list[int], list[int], list[int]]:
    out: list[list[int]] = [[], [], [], []]
    for field, wire_t, v in iter_fields(data):
        if 1 <= field <= 4:
            out[field - 1].extend(decode_packed_uint64(v))
    return out[0], out[1], out[2], out[3]


def encode_cache(ids: list[int]) -> bytes:
    return Writer().packed(1, ids).finish()


def decode_cache(data: bytes) -> list[int]:
    ids: list[int] = []
    for field, wire, v in iter_fields(data):
        if field == 1:
            ids.extend(decode_packed_uint64(v))
    return ids


def encode_max_slices_response(max_slices: dict[str, int]) -> bytes:
    w = Writer()
    # proto3 map entries: sorted by key (both gogo and google.protobuf
    # deterministic order), value field emitted even when 0.
    for k in sorted(max_slices):
        entry = Writer().string(1, k).varint(2, max_slices[k], force=True).finish()
        w.message(1, entry)
    return w.finish()


def decode_max_slices_response(data: bytes) -> dict[str, int]:
    out: dict[str, int] = {}
    for field, wire, v in iter_fields(data):
        if field == 1:
            key, val = "", 0
            for f2, w2, v2 in iter_fields(v):
                if f2 == 1:
                    key = v2.decode()
                elif f2 == 2:
                    val = v2
            out[key] = val
    return out


# -- node status (internal/private.proto:69-90 Frame/Index/NodeStatus) -------


def encode_node_status(host: str, state: str, indexes: list[dict]) -> bytes:
    """internal.NodeStatus: the gossip/status payload (private.proto:82-86).

    ``indexes`` items: {"name", "meta": index-meta dict, "maxSlice",
    "frames": [{"name", "meta": frame-meta dict}], "slices": [int]}.
    """
    w = Writer().string(1, host).string(2, state)
    for idx in indexes:
        iw = Writer().string(1, idx.get("name", ""))
        meta = idx.get("meta")
        if meta is not None:  # unset submessage is omitted (proto3 presence)
            iw.message(
                2, encode_index_meta(meta.get("columnLabel", ""), meta.get("timeQuantum", ""))
            )
        iw.varint(3, idx.get("maxSlice", 0))
        for fr in idx.get("frames", []):
            fmeta = fr.get("meta")
            fw = Writer().string(1, fr.get("name", ""))
            if fmeta is not None:
                fw.message(
                    2,
                    encode_frame_meta(
                        fmeta.get("rowLabel", ""),
                        fmeta.get("inverseEnabled", False),
                        fmeta.get("cacheType", ""),
                        fmeta.get("cacheSize", 0),
                        fmeta.get("timeQuantum", ""),
                    ),
                )
            iw.message(4, fw.finish())
        # repeated scalar -> packed in proto3 (zero entries survive the
        # length-prefixed encoding; matches the reference encoder's bytes).
        iw.packed(5, idx.get("slices", []))
        w.message(3, iw.finish())
    return w.finish()


def decode_node_status(data: bytes) -> dict:
    out: dict = {"host": "", "state": "", "indexes": []}
    for field, wire, v in iter_fields(data):
        if field == 1:
            out["host"] = v.decode()
        elif field == 2:
            out["state"] = v.decode()
        elif field == 3:
            out["indexes"].append(_decode_index_msg(v))
    return out


def _decode_index_msg(v: bytes) -> dict:
    """internal.Index (private.proto Frame/Index); ``meta`` keys appear
    only when the submessage was present on the wire (re-encode parity)."""
    idx: dict = {"name": "", "maxSlice": 0, "frames": [], "slices": []}
    for f2, w2, v2 in iter_fields(v):
        if f2 == 1:
            idx["name"] = v2.decode()
        elif f2 == 2:
            idx["meta"] = decode_index_meta(v2)
        elif f2 == 3:
            idx["maxSlice"] = v2
        elif f2 == 4:
            fr: dict = {"name": ""}
            for f3, w3, v3 in iter_fields(v2):
                if f3 == 1:
                    fr["name"] = v3.decode()
                elif f3 == 2:
                    fr["meta"] = decode_frame_meta(v3)
            idx["frames"].append(fr)
        elif f2 == 5:
            # packed (reference encoding) or unpacked (also legal proto3)
            idx["slices"].extend(decode_packed_uint64(v2))
    return idx


def encode_cluster_status(nodes: list[dict]) -> bytes:
    """internal.ClusterStatus (private.proto:88-90): the gossip
    LocalState/MergeRemoteState payload.  ``nodes`` items use the
    encode_node_status dict shape."""
    w = Writer()
    for n in nodes:
        w.message(
            1, encode_node_status(n.get("host", ""), n.get("state", ""), n.get("indexes", []))
        )
    return w.finish()


def decode_cluster_status(data: bytes) -> list[dict]:
    return [decode_node_status(v) for field, wire, v in iter_fields(data) if field == 1]
