"""TPU kernel layer: dense packed-bitmap set algebra and popcounts.

This package replaces the reference's L0/L1 hot path — the AMD64 SIMD
popcount kernels (roaring/assembly_amd64.s) and the per-container set-op
kernels (roaring/roaring.go:1192-1558) — with XLA/Pallas computations over
dense packed ``uint32`` arrays.

- `bitwise` — jnp/XLA implementations (work on any backend; XLA fuses the
  elementwise op + population_count + reduction into one HBM pass).
- `pallas_kernels` — hand-written Pallas TPU kernels for the fused
  op+popcount reductions (the `popcntAndSliceAsm` analog), used on TPU.
- `dispatch` — picks Pallas on TPU, jnp elsewhere.
"""

from pilosa_tpu.ops.bitwise import (  # noqa: F401
    WORD_BITS,
    WORDS_PER_SLICE,
    bit_and,
    bit_or,
    bit_xor,
    bit_andnot,
    popcount_words,
    make_range_mask,
    pack_positions,
    unpack_positions,
    pack_rows_matrix,
)

# The public fused-count entry points route through the backend dispatcher
# (Pallas on TPU, jnp elsewhere); pilosa_tpu.ops.bitwise keeps the raw jnp
# implementations as the portable fallback / ground-truth layer.
from pilosa_tpu.ops.dispatch import (  # noqa: F401
    count,
    count_and,
    count_or,
    count_xor,
    count_andnot,
    batch_intersection_count,
)
