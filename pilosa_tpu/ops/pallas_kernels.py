"""Hand-written Pallas TPU kernels for the fused popcount reductions.

These are the TPU-native equivalents of the reference's hand-written AMD64
SIMD loops (roaring/assembly_amd64.s:25-115): one pass over HBM that applies
the bitwise op, popcounts each word on the VPU, and reduces to a scalar per
row — no intermediate materialization.

A packed row of one slice is 32768 uint32 words, viewed as a (256, 128)
tile-aligned block (int32 min tile is (8, 128)).  The grid iterates over the
leading (row/slice) axis; Pallas double-buffers the HBM→VMEM DMAs across
grid steps, so the kernel streams at HBM bandwidth.

Fallback: on non-TPU backends (or non-tileable word counts) `dispatch`
routes to the jnp implementations in `bitwise`, the analog of the reference
gating its asm path on a CPUID check (roaring/assembly_asm.go:20,
assembly_generic.go).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_SUBLANES = 8  # int32/uint32 min sublane count


# Shared pair-op table (operators lower identically in kernel bodies).
from pilosa_tpu.ops.bitwise import apply_pair_op as _op_apply  # noqa: E402


def _partial_tile(words):
    # words: (1, sub, 128) uint32 -> (8, 128) int32 partial popcount sums.
    # Reducing only across sublane groups keeps the store tile-aligned
    # ((8,128) is the int32 min tile); the final (8,128)->scalar fold is left
    # to XLA outside the kernel where it costs nothing.
    pc = lax.population_count(words).astype(jnp.int32)
    sub = words.shape[1]
    return pc.reshape(sub // 8, 8, _LANES).sum(axis=0)


def _count2_kernel(op, a_ref, b_ref, out_ref):
    out_ref[0] = _partial_tile(_op_apply(op, a_ref[...], b_ref[...]))


def _count1_kernel(a_ref, out_ref):
    out_ref[0] = _partial_tile(a_ref[...])


def _tileable(n_words: int) -> bool:
    return n_words % (_LANES * _SUBLANES) == 0


def rm_words(rm) -> int:
    """Logical word count W of a row matrix in either layout (see _rm4)."""
    return rm.shape[-1] if rm.ndim == 3 else rm.shape[-2] * rm.shape[-1]


def _rm4(rm):
    """Canonical TILED row-matrix form uint32[S, R, W/128, 128].

    Device arrays BORN in this 4D form avoid the relayout XLA otherwise
    inserts when a [S, R, W] array is reshaped inside jit: the physical
    (8, 128) tiling of (R, W) differs from that of (W/128, 128), so the
    reshape materializes a full tiled copy of the matrix in HBM — the
    round-2 OOM at 1024 slices was exactly this 8 GB temp
    (BASELINE.md round-3 note).  Jax engines therefore store matrices 4D
    (engine.matrix) and this helper is an identity no-op; 3D callers
    (tests, numpy-built transients) still work and pay the transient.
    """
    if rm.ndim == 4:
        return rm
    s, r, w = rm.shape
    return rm.reshape(s, r, w // _LANES, _LANES)


@functools.partial(jax.jit, static_argnames=("op", "interpret", "tiled"))
def fused_count2(op: str, a, b, interpret: bool = False, tiled: bool = False):
    """sum(popcount(op(a, b))) over the last axis via a Pallas kernel.

    a: uint32[..., W] with W % 1024 == 0; b: same shape as a, OR uint32[W]
    (a single shared operand, e.g. TopN's src row counted against a whole
    stack of candidate rows).  The shared case streams the one b block into
    VMEM once per grid step instead of materializing a K-way broadcast in
    HBM.  Returns int32[...] (a's shape minus the word axis).

    ``tiled=True`` declares that the trailing TWO axes are the word axis
    in canonical tiled form [..., W/128, 128] (see _rm4): rows sliced out
    of a 4D engine matrix keep their relayout-free path, and b is
    [..., W/128, 128] correspondingly.
    """
    if tiled:
        sub = a.shape[-2] * a.shape[-1] // _LANES
        shape = a.shape[:-2] + (a.shape[-2] * a.shape[-1],)
        shared_b = b.ndim == 2 and a.ndim > 2
    else:
        shape = a.shape
        sub = shape[-1] // _LANES
        shared_b = b.ndim == 1 and a.ndim > 1
    w = sub * _LANES
    m = 1
    for d in shape[:-1]:
        m *= d
    a3 = a.reshape(m, sub, _LANES)
    if shared_b:
        b3 = b.reshape(1, sub, _LANES)
        b_spec = pl.BlockSpec((1, sub, _LANES), lambda i: (0, 0, 0))
    else:
        b3 = jnp.broadcast_to(b, a.shape).reshape(m, sub, _LANES)
        b_spec = pl.BlockSpec((1, sub, _LANES), lambda i: (i, 0, 0))
    out = pl.pallas_call(
        functools.partial(_count2_kernel, op),
        out_shape=jax.ShapeDtypeStruct((m, 8, _LANES), jnp.int32),
        grid=(m,),
        in_specs=[
            pl.BlockSpec((1, sub, _LANES), lambda i: (i, 0, 0)),
            b_spec,
        ],
        out_specs=pl.BlockSpec((1, 8, _LANES), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(a3, b3)
    return out.sum(axis=(1, 2)).reshape(shape[:-1])


def _resident_count_kernel(op, n_pairs, pairs_ref, rows_ref, out_ref):
    s, k = pl.program_id(0), pl.program_id(1)

    @pl.when((s == 0) & (k == 0))
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    c_sub = rows_ref.shape[2]

    def body(q, carry):
        a = rows_ref[0, pairs_ref[q, 0]]
        b = rows_ref[0, pairs_ref[q, 1]]
        pc = lax.population_count(_op_apply(op, a, b)).astype(jnp.int32)
        part = pc.reshape(c_sub // 8, 8, _LANES).sum(axis=0)
        out_ref[q] = out_ref[q] + part
        return carry

    lax.fori_loop(0, n_pairs, body, 0)


def _resident_chunk_sub(
    n_rows: int, w: int, batch: int = 0, budget_bytes: int = 8 * 1024 * 1024
) -> int:
    """Largest power-of-two sublane chunk (multiple of 8, dividing w/128)
    whose all-rows block fits the VMEM budget; 0 if even 8 doesn't fit.

    The (batch, 8, 128) int32 accumulator block is held fully resident
    across every grid step (constant output index map), so its footprint
    comes out of the same budget — large fused batches must fall back to
    the per-query gather kernel whose output block is (1, 8, 128).

    Budget 8 MB: the block is double-buffered across grid steps, so the
    worst case is 2*(8MB - out) + out <= 16 MB VMEM.  Measured at the
    1024-slice bench shape: 4 MB blocks (this budget) run at 80% of the
    HBM roofline vs 53% with the previous 4 MB budget's 2 MB blocks —
    the v5e DMA descriptor ladder again (BASELINE.md round-3 notes)."""
    out_bytes = batch * 8 * _LANES * 4
    total_sub = w // _LANES
    best = 0
    c = 8
    while c <= total_sub:
        if total_sub % c == 0 and n_rows * c * _LANES * 4 + out_bytes <= budget_bytes:
            best = c
        c *= 2
    return best


def resident_strategy(n_rows: int, w: int, batch: int) -> bool:
    """Whether the VMEM-resident kernel beats the per-query gather for a
    pair-count batch: streaming ALL rows once must beat gathering 2 rows
    per query (R < 2B) and an all-rows chunk must fit the VMEM budget.
    Shared by single-chip dispatch and the shard_map'd mesh tier so the
    heuristic can't drift between them."""
    return n_rows < 2 * batch and bool(_resident_chunk_sub(n_rows, w, batch))


@functools.partial(jax.jit, static_argnames=("op", "interpret"))
def fused_resident_count2(op: str, row_matrix, pairs, interpret: bool = False):
    """Row-resident variant of :func:`fused_gather_count2` for small row
    working sets (the common case: a hot frame has far fewer distinct rows
    than the query batch has row references).

    Instead of DMAing two operand rows per (query, slice) grid step —
    2*B*S row reads — this streams the ENTIRE row matrix HBM→VMEM exactly
    once (grid = (slice, word-chunk), block = all rows of one chunk) and
    answers every query in the batch from VMEM with dynamic row indexing.
    HBM traffic drops from 2*B to R row-equivalents per slice, which for
    the headline bench shape (R=64 rows, B=256 queries) is ~8x less; the
    kernel then runs at VPU popcount speed instead of HBM gather speed.
    TPU-native analog of the reference's rowCache keeping hot rows out of
    the mmap (fragment.go:338-367) — here "cache" is VMEM residency.
    """
    rm4 = _rm4(row_matrix)
    n_slices, n_rows = rm4.shape[:2]
    w = rm4.shape[2] * rm4.shape[3]
    b = pairs.shape[0]
    c_sub = _resident_chunk_sub(n_rows, w, b)
    if c_sub == 0:
        raise ValueError("row matrix + accumulator too large for resident kernel")
    n_chunks = (w // _LANES) // c_sub
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_slices, n_chunks),
        in_specs=[
            pl.BlockSpec((1, n_rows, c_sub, _LANES), lambda s, k, pr: (s, 0, k, 0)),
        ],
        out_specs=pl.BlockSpec((b, 8, _LANES), lambda s, k, pr: (0, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_resident_count_kernel, op, b),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 8, _LANES), jnp.int32),
        interpret=interpret,
    )(pairs, rm4)
    return out.sum(axis=(1, 2))


def _gather_count_kernel(op, pairs_ref, a_ref, b_ref, out_ref):
    s = pl.program_id(1)
    part = _partial_tile(_op_apply(op, a_ref[0], b_ref[0]))

    @pl.when(s == 0)
    def _():
        out_ref[0] = part

    @pl.when(s != 0)
    def _():
        out_ref[0] = out_ref[0] + part


@functools.partial(jax.jit, static_argnames=("op", "interpret"))
def fused_gather_count2(op: str, row_matrix, pairs, interpret: bool = False):
    """Per-query ``sum_s popcount(op(rm[s, p0], rm[s, p1]))`` without
    materializing the gathered operands.

    row_matrix: uint32[n_slices, n_rows, W] with W % 1024 == 0;
    pairs: int32[B, 2] row ids.  Returns int32[B] counts summed over
    slices and words.

    The batched ``Count(Intersect(Bitmap(r1), Bitmap(r2)))`` hot path
    (executor.go:576-605 + roaring/assembly_amd64.s:60-77 analog).  The
    XLA form (`jnp.take` → AND → popcount) writes both gathered stacks to
    HBM before reading them back; this kernel instead scalar-prefetches
    the pair ids and DMAs each operand row HBM→VMEM exactly once per
    (query, slice) grid step, halving HBM traffic.  The slice axis is the
    minor grid dimension so the per-query accumulator tile stays resident
    in VMEM across the reduction.
    """
    rm4 = _rm4(row_matrix)
    n_slices, n_rows, sub = rm4.shape[:3]
    b = pairs.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, n_slices),
        in_specs=[
            pl.BlockSpec((1, 1, sub, _LANES), lambda q, s, pr: (s, pr[q, 0], 0, 0)),
            pl.BlockSpec((1, 1, sub, _LANES), lambda q, s, pr: (s, pr[q, 1], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 8, _LANES), lambda q, s, pr: (q, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_gather_count_kernel, op),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 8, _LANES), jnp.int32),
        interpret=interpret,
    )(pairs, rm4, rm4)
    return out.sum(axis=(1, 2))


def _topn_counts_kernel(rows_ref, src_ref, out_ref):
    s, k = pl.program_id(1), pl.program_id(2)

    @pl.when((s == 0) & (k == 0))
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    inter = rows_ref[0] & src_ref[0][None]  # [r_c, c_sub, 128]
    pc = lax.population_count(inter).astype(jnp.int32)
    r, c_sub, _ = pc.shape
    out_ref[...] = out_ref[...] + pc.reshape(r, c_sub // 8, 8, _LANES).sum(axis=1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_topn_counts(row_matrix, src, interpret: bool = False):
    """|row & src| for EVERY row over every slice — TopN's candidate
    scoring phase when the whole row set is scored (fragment.go:493-625's
    device half).

    row_matrix: [S, R, W] or tiled [S, R, W/128, 128]; src: [S, W] or
    tiled [S, W/128, 128].  Returns int32[R].  One auto-pipelined pass
    over the matrix in ~2 MB blocks (near-roofline HBM streaming) with
    the per-row-chunk accumulator tile resident in VMEM — the jnp
    broadcast form ran at 9% of roofline on this shape (BASELINE.md
    round-3 note).  The row axis is chunked too (outermost grid axis, so
    the accumulator block stays resident across its (slice, word-chunk)
    reduction): tall row sets would otherwise need an over-VMEM block.
    """
    rm4 = _rm4(row_matrix)
    if src.ndim == 2:
        src = src.reshape(src.shape[0], src.shape[1] // _LANES, _LANES)
    n_slices, n_rows, sub = rm4.shape[:3]
    budget = 4 * 1024 * 1024
    # Row chunk: halve (stays a divisor of R) until the minimal
    # (r_c, 8, 128) input block + (r_c, 8, 128) accumulator fit.
    r_c = n_rows
    while r_c > 1 and r_c % 2 == 0 and 2 * r_c * 8 * _LANES * 4 > budget:
        r_c //= 2
    c_sub = 8
    c = 8
    while c <= sub:
        if sub % c == 0 and r_c * c * _LANES * 4 + r_c * 8 * _LANES * 4 <= budget:
            c_sub = c
        c *= 2
    n_chunks = sub // c_sub
    out = pl.pallas_call(
        _topn_counts_kernel,
        grid=(n_rows // r_c, n_slices, n_chunks),
        in_specs=[
            pl.BlockSpec((1, r_c, c_sub, _LANES), lambda r, s, k: (s, r, k, 0)),
            pl.BlockSpec((1, c_sub, _LANES), lambda r, s, k: (s, k, 0)),
        ],
        out_specs=pl.BlockSpec((r_c, 8, _LANES), lambda r, s, k: (r, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_rows, 8, _LANES), jnp.int32),
        interpret=interpret,
    )(rm4, src)
    return out.sum(axis=(1, 2))


def _gather_src_counts_kernel(pos_ref, row_ref, src_ref, out_ref):
    out_ref[0, 0] = _partial_tile((row_ref[0, 0] & src_ref[0])[None])


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_gather_src_counts(row_matrix, pos, src_stack, interpret: bool = False):
    """Per-(slice, candidate) ``|rm[s, pos[k]] & src[s]|`` in ONE launch —
    TopN's candidate scoring across every slice at once
    (fragment.go:493-625's Src.IntersectionCount phase, cross-slice
    fused; the per-(slice, chunk) dispatch this replaces paid one tunnel
    round trip per slice).

    row_matrix: uint32[S, R, W] (or tiled 4D); pos: int32[K] candidate
    row slots; src_stack: uint32[S, W] (or tiled [S, W/128, 128]).
    Returns int32[S, K].
    """
    rm4 = _rm4(row_matrix)
    n_slices, n_rows, sub = rm4.shape[:3]
    if src_stack.ndim == 2:
        src_stack = src_stack.reshape(n_slices, sub, _LANES)
    k = pos.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(k, n_slices),
        in_specs=[
            pl.BlockSpec((1, 1, sub, _LANES), lambda q, s, pr: (s, pr[q], 0, 0)),
            pl.BlockSpec((1, sub, _LANES), lambda q, s, pr: (s, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 8, _LANES), lambda q, s, pr: (q, s, 0, 0)),
    )
    out = pl.pallas_call(
        _gather_src_counts_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((k, n_slices, 8, _LANES), jnp.int32),
        interpret=interpret,
    )(pos, rm4, src_stack)
    return out.sum(axis=(2, 3)).T  # [S, K]


def _gather_rowmajor_kernel(op, depth, pairs_ref, rm_ref, out_ref, buf, sems):
    q = pl.program_id(0)
    n_q = pl.num_programs(0)

    def dma(i, o):
        # Whole row (ALL slices) in ONE descriptor: rm is row-major
        # [R, S, sub, 128], so rm[r] is a single contiguous S*W*4-byte
        # region.  The v5e DMA engine spends ~1 us of serial processing
        # per descriptor regardless of size (measured; BASELINE.md
        # round-3 note), so fewer/bigger transfers are the whole game:
        # per-(query, slice) 128 KB descriptors cap well under 20% of HBM
        # bandwidth, one 512 KB descriptor per operand reaches ~40%, 2 MB
        # reaches ~76%.
        return pltpu.make_async_copy(
            rm_ref.at[pairs_ref[i, o]], buf.at[i % depth, o], sems.at[i % depth, o]
        )

    @pl.when(q == 0)
    def _():
        for d in range(depth - 1):
            for o in range(2):
                dma(d, o).start()

    @pl.when(q + depth - 1 < n_q)
    def _():
        for o in range(2):
            dma(q + depth - 1, o).start()

    for o in range(2):
        dma(q, o).wait()
    a = buf[q % depth, 0]
    b = buf[q % depth, 1]
    pc = lax.population_count(_op_apply(op, a, b)).astype(jnp.int32)
    s, sub, _ = pc.shape
    out_ref[0] = pc.reshape(s * sub // 8, 8, _LANES).sum(axis=0)


@functools.partial(jax.jit, static_argnames=("op", "depth", "interpret"))
def fused_gather_count2_rowmajor(
    op: str, row_major, pairs, depth: int = 2, interpret: bool = False
):
    """Pair counts over a ROW-MAJOR tiled matrix uint32[R, S, W/128, 128].

    The gather regime's fast path for working sets too tall for the
    resident kernel: one hand-pipelined DMA per (query, operand) moves the
    operand row across ALL slices in a single contiguous descriptor, with
    ``depth`` queries in flight.  The slice-major form's per-(query,
    slice) descriptors bound that kernel by the DMA engine's serial
    descriptor rate, not HBM bandwidth (see _gather_rowmajor_kernel);
    row-major storage trades the slice-sharding-friendly axis order for
    descriptor-rate relief — callers that keep matrices slice-sharded on
    a mesh stay on :func:`fused_gather_count2`.

    pairs: int32[B, 2].  Returns int32[B].  VMEM: 2*depth row buffers
    (depth*2*S*W*4 bytes) — callers bound S*W accordingly.
    """
    n_rows, n_slices, sub = row_major.shape[:3]
    b = pairs.shape[0]
    # A pipeline deeper than the batch would start DMAs for queries past
    # the id array (and never wait on them — outstanding copies at kernel
    # exit corrupt or hang real hardware).
    depth = max(1, min(depth, b))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, 8, _LANES), lambda q, pr: (q, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((depth, 2, n_slices, sub, _LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA((depth, 2)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_gather_rowmajor_kernel, op, depth),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 8, _LANES), jnp.int32),
        interpret=interpret,
    )(pairs, row_major)
    return out.sum(axis=(1, 2))


def _gather_multi_rowmajor_kernel(op, n_ops, depth, idx_ref, rm_ref, out_ref, buf, sems):
    q = pl.program_id(0)
    n_q = pl.num_programs(0)
    fold = _FOLD_OPS[op]

    def dma(i, j):
        return pltpu.make_async_copy(
            rm_ref.at[idx_ref[i, j]], buf.at[i % depth, j], sems.at[i % depth, j]
        )

    @pl.when(q == 0)
    def _():
        for d in range(depth - 1):
            for j in range(n_ops):
                dma(d, j).start()

    @pl.when(q + depth - 1 < n_q)
    def _():
        for j in range(n_ops):
            dma(q + depth - 1, j).start()

    for j in range(n_ops):
        dma(q, j).wait()
    acc = buf[q % depth, 0]
    for j in range(1, n_ops):
        acc = fold(acc, buf[q % depth, j])
    pc = lax.population_count(acc).astype(jnp.int32)
    s, sub, _ = pc.shape
    out_ref[0] = pc.reshape(s * sub // 8, 8, _LANES).sum(axis=0)


@functools.partial(jax.jit, static_argnames=("op", "depth", "interpret"))
def fused_gather_count_multi_rowmajor(
    op: str, row_major, idx, depth: int = 2, interpret: bool = False
):
    """Left-fold counts over a ROW-MAJOR matrix [R, S, W/128, 128]: the
    K-operand form of :func:`fused_gather_count2_rowmajor` (N-ary
    Intersect/Union/Difference and fused Range view covers in the
    streaming gather regime).  One contiguous DMA descriptor per
    (query, operand); idx: int32[B, K] padded with fold-idempotent ids.
    VMEM: depth*K row buffers — callers bound K * S * W * 4."""
    n_rows, n_slices, sub = row_major.shape[:3]
    b, n_ops = idx.shape
    depth = max(1, min(depth, b))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, 8, _LANES), lambda q, pr: (q, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((depth, n_ops, n_slices, sub, _LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA((depth, n_ops)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_gather_multi_rowmajor_kernel, op, n_ops, depth),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 8, _LANES), jnp.int32),
        interpret=interpret,
    )(idx, row_major)
    return out.sum(axis=(1, 2))


# Left-fold step for the multi-operand gather kernels: how operand j>0
# combines into the accumulator.  "andnot" folds acc &~ row (Difference's
# left-associative chain); all are pad-idempotent for the right pad id
# (and/or: any repeated operand; andnot: repeat any NON-first operand).
_FOLD_OPS = {
    "and": lambda acc, row: acc & row,
    "or": lambda acc, row: acc | row,
    "andnot": lambda acc, row: acc & ~row,
}


def _gather_multi_kernel(op, n_ops, idx_ref, row_ref, out_ref, acc_ref):
    s, j = pl.program_id(1), pl.program_id(2)
    fold = _FOLD_OPS[op]

    @pl.when(j == 0)
    def _():
        acc_ref[...] = row_ref[0, 0]

    @pl.when(j != 0)
    def _():
        acc_ref[...] = fold(acc_ref[...], row_ref[0, 0])

    @pl.when((j == n_ops - 1) & (s == 0))
    def _():
        out_ref[0] = _partial_tile(acc_ref[...][None])

    @pl.when((j == n_ops - 1) & (s != 0))
    def _():
        out_ref[0] = out_ref[0] + _partial_tile(acc_ref[...][None])


@functools.partial(jax.jit, static_argnames=("op", "interpret"))
def fused_gather_count_multi(op: str, row_matrix, idx, interpret: bool = False):
    """Per-query ``sum_s popcount(fold_j rm[s, idx[q, j]])`` for a
    left-fold of up to K gathered rows per query — the fused form of
    Count over N-operand Intersect/Union/Difference trees AND the
    time-quantum Range view cover (op="or").

    row_matrix: uint32[n_slices, n_rows, W] (W % 1024 == 0);
    idx: int32[B, K] row ids; short operand lists pad with an id whose
    repeat is a no-op for the fold (and/or: any operand; andnot: any
    non-first operand).  Returns int32[B].

    One row DMA per (query, slice, operand) grid step folds into a VMEM
    scratch accumulator; at the last operand the accumulated result is
    popcounted into the per-query output tile, which stays resident
    across the slice axis.  The XLA fallback materializes the whole
    [S, B, K, W] gather in HBM first.
    """
    rm4 = _rm4(row_matrix)
    n_slices, n_rows, sub = rm4.shape[:3]
    b, n_ops = idx.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, n_slices, n_ops),
        in_specs=[
            pl.BlockSpec((1, 1, sub, _LANES), lambda q, s, j, pr: (s, pr[q, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 8, _LANES), lambda q, s, j, pr: (q, 0, 0)),
        scratch_shapes=[pltpu.VMEM((sub, _LANES), jnp.uint32)],
    )
    out = pl.pallas_call(
        functools.partial(_gather_multi_kernel, op, n_ops),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 8, _LANES), jnp.int32),
        interpret=interpret,
    )(idx, rm4)
    return out.sum(axis=(1, 2))


def fused_gather_count_or(row_matrix, idx, interpret: bool = False):
    """OR-fold convenience wrapper (the fused Range cover count)."""
    return fused_gather_count_multi("or", row_matrix, idx, interpret=interpret)


def _gather_tree_kernel(k, leaves_ref, opc_ref, row_ref, out_ref, buf_ref):
    from pilosa_tpu.ops.bitwise import tree_select

    q, s, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    buf_ref[j] = row_ref[0, 0]

    def fold():
        vals = [buf_ref[t] for t in range(k)]
        off = 0
        n = k // 2
        while n >= 1:
            vals = [
                tree_select(opc_ref[q, off + t], vals[2 * t], vals[2 * t + 1])
                for t in range(n)
            ]
            off += n
            n //= 2
        return _partial_tile(vals[0][None])

    @pl.when((j == k - 1) & (s == 0))
    def _():
        out_ref[0] = fold()

    @pl.when((j == k - 1) & (s != 0))
    def _():
        out_ref[0] = out_ref[0] + fold()


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_gather_count_tree(row_matrix, leaves, opc, interpret: bool = False):
    """Per-query ``sum_s popcount(tree(rows))`` for an ARBITRARY nested
    expression tree per query — the fused form of Count over any nesting
    of Intersect/Union/Xor/Difference (executor.go:261-276's uniform
    call-tree evaluation, one kernel launch for the whole batch).

    row_matrix: uint32[n_slices, n_rows, W] (or tiled 4D);
    leaves: int32[B, K] row ids of a PERFECT binary tree (K = 2^D);
    opc: int32[B, K-1] node opcodes level-major bottom-up
    (bitwise.gather_count_tree documents the encoding; TREE_PASS pads).
    Returns int32[B].

    One row DMA per (query, slice, leaf) grid step lands in a VMEM leaf
    buffer; at the last leaf the whole fold (statically unrolled — K is
    small) runs in VMEM and accumulates into the per-query output tile,
    which stays resident across the slice axis.  Per-node opcodes are
    scalar-prefetched, so one compiled kernel serves every tree shape of
    the same depth bucket.
    """
    rm4 = _rm4(row_matrix)
    n_slices, n_rows, sub = rm4.shape[:3]
    b, k = leaves.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_slices, k),
        in_specs=[
            pl.BlockSpec(
                (1, 1, sub, _LANES), lambda q, s, j, lv, oc: (s, lv[q, j], 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec((1, 8, _LANES), lambda q, s, j, lv, oc: (q, 0, 0)),
        scratch_shapes=[pltpu.VMEM((k, sub, _LANES), jnp.uint32)],
    )
    out = pl.pallas_call(
        functools.partial(_gather_tree_kernel, k),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, 8, _LANES), jnp.int32),
        interpret=interpret,
    )(leaves, opc, rm4)
    return out.sum(axis=(1, 2))


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_count1(a, interpret: bool = False):
    """sum(popcount(a)) over the last axis via a Pallas kernel."""
    shape = a.shape
    w = shape[-1]
    m = 1
    for d in shape[:-1]:
        m *= d
    sub = w // _LANES
    a3 = a.reshape(m, sub, _LANES)
    out = pl.pallas_call(
        _count1_kernel,
        out_shape=jax.ShapeDtypeStruct((m, 8, _LANES), jnp.int32),
        grid=(m,),
        in_specs=[pl.BlockSpec((1, sub, _LANES), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, 8, _LANES), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(a3)
    return out.sum(axis=(1, 2)).reshape(shape[:-1])
