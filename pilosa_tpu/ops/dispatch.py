"""Backend dispatch: Pallas kernels on TPU, jnp/XLA elsewhere.

The analog of the reference's init-time CPUID gate
(roaring/assembly_asm.go:17-23: use asm if POPCNT is available, else the Go
SWAR fallback).  Here the "feature detect" is the JAX default backend; the
jnp path also serves TPU-less CI (tests force JAX_PLATFORMS=cpu).

Set ``PILOSA_TPU_NO_PALLAS=1`` (or ``true``) to force the jnp path on TPU;
the variable is read on every call so it can be toggled for benchmarking.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from pilosa_tpu.ops import bitwise
from pilosa_tpu.ops.pallas_kernels import (
    _tileable,
    fused_count1,
    fused_count2,
    fused_gather_count2,
    fused_gather_count_multi,
    fused_resident_count2,
    resident_strategy,
    rm_words,
)


def _rm_dims(row_matrix) -> tuple[int, int, int]:
    """(n_slices, n_rows, W) of a row matrix in 3D logical or 4D tiled
    form (see pallas_kernels._rm4)."""
    return row_matrix.shape[0], row_matrix.shape[1], rm_words(row_matrix)


def _rm3(row_matrix):
    """Logical [S, R, W] view (the jnp/numpy fallbacks and the Gram path
    index the word axis flat).  On TPU this reshape materializes a tiled
    relayout copy inside jit — callers only use it off the kernel path."""
    if row_matrix.ndim == 3:
        return row_matrix
    s, r = row_matrix.shape[:2]
    return row_matrix.reshape(s, r, -1)


@functools.lru_cache(maxsize=None)
def _backend_is_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    # analysis-ok: exception-hygiene: backend feature probe; False routes to the portable lane
    except Exception:
        return False


def use_pallas() -> bool:
    # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
    if os.environ.get("PILOSA_TPU_NO_PALLAS", "").lower() in ("1", "true", "yes"):
        return False
    return _backend_is_tpu()


def count(x):
    if use_pallas() and _tileable(x.shape[-1]):
        return fused_count1(x)
    return bitwise.count(x)


def count_and(a, b):
    if use_pallas() and _tileable(a.shape[-1]):
        return fused_count2("and", a, b)
    return bitwise.count_and(a, b)


def count_or(a, b):
    if use_pallas() and _tileable(a.shape[-1]):
        return fused_count2("or", a, b)
    return bitwise.count_or(a, b)


def count_xor(a, b):
    if use_pallas() and _tileable(a.shape[-1]):
        return fused_count2("xor", a, b)
    return bitwise.count_xor(a, b)


def count_andnot(a, b):
    if use_pallas() and _tileable(a.shape[-1]):
        return fused_count2("andnot", a, b)
    return bitwise.count_andnot(a, b)


def gather_count_and(row_matrix, pairs):
    """Batched Count(Intersect(...)) over a [n_slices, n_rows, W] row
    matrix for int32[B, 2] row-id pairs — the headline query hot path."""
    return gather_count("and", row_matrix, pairs)


# Gram strategy gate: all-pairs count work may exceed the requested batch
# by this factor before the MXU path stops paying off; one SLICE's
# unpacked int8 bits must fit a transient-HBM budget (the chunked builder
# streams slice by slice — see bitwise.pair_gram), and per-pair counts
# must stay inside int32 (≤ 2047 slices × 2^20 bits).
_GRAM_FACTOR = 16
_GRAM_BYTES_BUDGET = 1536 * 1024 * 1024
_GRAM_SLICES_MAX = 2047


def _use_gram(n_slices: int, n_rows: int, w: int, batch: int) -> bool:
    # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
    if os.environ.get("PILOSA_TPU_NO_GRAM", "").lower() in ("1", "true", "yes"):  # analysis-ok: env-knob-outside-config: kernel-layer kill switch shared with non-server embedders
        return False
    return (
        n_rows * n_rows <= _GRAM_FACTOR * batch
        and n_rows * w * 32 <= _GRAM_BYTES_BUDGET
        and n_slices <= _GRAM_SLICES_MAX
    )


# The Pallas kernels scalar-prefetch the pair ids into SMEM (~1 MiB);
# large batches are evaluated in chunks of this many queries.
_GATHER_BATCH_MAX = 1024


def gather_count(op, row_matrix, pairs, allow_gram: bool = True):
    """Batched Count(<op>(Bitmap, Bitmap)) — and/or/xor/andnot (the
    fused forms of Intersect/Union/Xor/Difference count batches).

    ``allow_gram=False`` skips the all-pairs MXU strategy — callers that
    manage their own Gram cache (the executor) or dispatch eagerly
    per-call want the cheaper direct kernels; the Gram branch pays off
    inside jitted query streams where XLA hoists it out of the loop.

    ``row_matrix`` may be 3D logical [S, R, W] or 4D tiled
    [S, R, W/128, 128] (the jax engines' relayout-free storage form)."""
    n_slices, n_rows, w = _rm_dims(row_matrix)
    # Matmul Gram strategy for tiny row sets: one int8 matmul computes ALL
    # pair counts; per-query answers are lookups.  Pure HLO on the row
    # matrix only (no Pallas dependency — any jax backend), so XLA hoists
    # it out of jitted query streams.
    if allow_gram and _use_gram(n_slices, n_rows, w, pairs.shape[0]):
        return bitwise.gram_pair_counts(op, bitwise.pair_gram(row_matrix), pairs)
    if use_pallas() and _tileable(w):
        b = pairs.shape[0]
        if b > _GATHER_BATCH_MAX:
            # Chunk oversized batches: the prefetched pair ids must fit
            # SMEM (observed hard failure at B=4096 on v5e).
            return jnp.concatenate(
                [
                    gather_count(
                        op, row_matrix, pairs[i : i + _GATHER_BATCH_MAX], allow_gram=False
                    )
                    for i in range(0, b, _GATHER_BATCH_MAX)
                ]
            )
        # Resident kernel wins whenever streaming ALL rows once beats
        # gathering 2 rows per query (shared predicate with the mesh
        # tier); otherwise fall back to the per-query gather.
        if resident_strategy(n_rows, w, b):
            return fused_resident_count2(op, row_matrix, pairs)
        return fused_gather_count2(op, row_matrix, pairs)
    return bitwise.gather_count(op, _rm3(row_matrix), pairs)


# Row-major kernel VMEM budget: depth(2) * k row buffers of S*W*4 bytes
# each must fit alongside the output tiles (~16 MB VMEM/core).
_ROWMAJOR_BUF_BYTES_MAX = 8 * 1024 * 1024


def rowmajor_ok(n_slices: int, w: int, k: int = 2) -> bool:
    """Whether the pipelined row-major gather kernels can buffer k
    operand rows of this width per pipeline slot (callers use it to
    decide the transient-matrix layout)."""
    return 2 * k * n_slices * w * 4 <= _ROWMAJOR_BUF_BYTES_MAX


def gather_count_rowmajor(op, row_major, pairs):
    """Batched pair counts over a ROW-MAJOR matrix [R, S, W] (3D logical)
    or [R, S, W/128, 128] (tiled): one contiguous DMA descriptor per
    operand covering every slice — the gather regime's fast path (v5e
    DMA descriptors process serially, so per-(query, slice) block DMAs
    cap well below roofline; see fused_gather_count2_rowmajor)."""
    from pilosa_tpu.ops.pallas_kernels import fused_gather_count2_rowmajor

    n_rows, n_slices = row_major.shape[:2]
    w = row_major.shape[-1] if row_major.ndim == 3 else row_major.shape[-2] * row_major.shape[-1]
    if use_pallas() and _tileable(w) and rowmajor_ok(n_slices, w):
        if row_major.ndim == 3:
            row_major = row_major.reshape(n_rows, n_slices, w // 128, 128)
        b = pairs.shape[0]
        if b > _GATHER_BATCH_MAX:
            return jnp.concatenate(
                [
                    fused_gather_count2_rowmajor(
                        op, row_major, pairs[i : i + _GATHER_BATCH_MAX]
                    )
                    for i in range(0, b, _GATHER_BATCH_MAX)
                ]
            )
        return fused_gather_count2_rowmajor(op, row_major, pairs)
    # Fallback: logical transpose to slice-major (non-TPU backends and
    # shapes the kernel can't buffer; engines gate the lane on
    # use_pallas() so the product path only lands here for oversized
    # rows).
    rm = _rm3(row_major) if row_major.ndim == 4 else row_major
    return bitwise.gather_count(op, jnp.swapaxes(rm, 0, 1), pairs)


def gather_count_multi_rowmajor(op, row_major, idx):
    """K-operand fold counts over a ROW-MAJOR matrix — the multi form of
    :func:`gather_count_rowmajor` (N-ary trees and Range covers in the
    streaming gather regime).  Buffers K rows per pipeline slot, so the
    row-width bound shrinks with K."""
    from pilosa_tpu.ops.pallas_kernels import fused_gather_count_multi_rowmajor

    n_rows, n_slices = row_major.shape[:2]
    w = row_major.shape[-1] if row_major.ndim == 3 else row_major.shape[-2] * row_major.shape[-1]
    b, k = idx.shape
    if use_pallas() and _tileable(w) and rowmajor_ok(n_slices, w, k):
        if row_major.ndim == 3:
            row_major = row_major.reshape(n_rows, n_slices, w // 128, 128)
        chunk = max(1, (2 * _GATHER_BATCH_MAX) // max(1, k))
        if b > chunk:
            return jnp.concatenate(
                [
                    fused_gather_count_multi_rowmajor(op, row_major, idx[i : i + chunk])
                    for i in range(0, b, chunk)
                ]
            )
        return fused_gather_count_multi_rowmajor(op, row_major, idx)
    rm = _rm3(row_major) if row_major.ndim == 4 else row_major
    return gather_count_multi(op, jnp.swapaxes(rm, 0, 1), idx)


def gather_count_multi(op, row_matrix, idx):
    """Batched Count over a left-fold of K gathered rows per query —
    N-operand Intersect/Union/Difference trees and the fused Range view
    cover (op="or").  idx: int32[B, K], padded with fold-idempotent
    ids (and/or: any operand; andnot: any non-first operand)."""
    b, k = idx.shape
    if use_pallas() and _tileable(rm_words(row_matrix)):
        # Prefetched ids must fit SMEM: the pair kernels prefetch B*2 ids
        # under _GATHER_BATCH_MAX, so bound B*K by the same id budget
        # (wide operand lists shrink the per-chunk batch).
        chunk = max(1, (2 * _GATHER_BATCH_MAX) // max(1, k))
        if b > chunk:
            return jnp.concatenate(
                [
                    gather_count_multi(op, row_matrix, idx[i : i + chunk])
                    for i in range(0, b, chunk)
                ]
            )
        return fused_gather_count_multi(op, row_matrix, idx)
    # XLA fallback materializes the gather: bound its transient HBM/host
    # footprint by chunking the batch (shared sizing helper).
    from pilosa_tpu.pilosa import OR_MULTI_BUDGET_DEVICE, or_multi_chunk_size

    s, _, w = _rm_dims(row_matrix)
    rm = _rm3(row_matrix)
    chunk = or_multi_chunk_size(s, k, w, OR_MULTI_BUDGET_DEVICE)
    if b > chunk:
        return jnp.concatenate(
            [
                bitwise.gather_count_multi(op, rm, idx[i : i + chunk])
                for i in range(0, b, chunk)
            ]
        )
    return bitwise.gather_count_multi(op, rm, idx)


def gather_count_or_multi(row_matrix, idx):
    """OR-fold convenience wrapper (the fused Range cover count)."""
    return gather_count_multi("or", row_matrix, idx)


def gather_count_tree(row_matrix, leaves, opc):
    """Batched Count over ARBITRARY nested expression trees — one
    dispatch per batch (executor.go:261-276 fused).  leaves: int32[B, K]
    (K = 2^D perfect-tree row ids); opc: int32[B, K-1] level-major
    bottom-up opcodes (see bitwise.gather_count_tree)."""
    from pilosa_tpu.ops.pallas_kernels import fused_gather_count_tree

    b, k = leaves.shape
    if use_pallas() and _tileable(rm_words(row_matrix)):
        # Prefetched ids per query: K leaves + K-1 opcodes ~ 2K — bound
        # by the same SMEM id budget as the pair/multi kernels.
        chunk = max(1, (2 * _GATHER_BATCH_MAX) // max(1, 2 * k - 1))
        if b > chunk:
            return jnp.concatenate(
                [
                    fused_gather_count_tree(
                        row_matrix, leaves[i : i + chunk], opc[i : i + chunk]
                    )
                    for i in range(0, b, chunk)
                ]
            )
        return fused_gather_count_tree(row_matrix, leaves, opc)
    # XLA fallback materializes the [S, chunk, K, W] gather: bound the
    # transient like the multi fallback does.
    from pilosa_tpu.pilosa import OR_MULTI_BUDGET_DEVICE, or_multi_chunk_size

    s, _, w = _rm_dims(row_matrix)
    rm = _rm3(row_matrix)
    chunk = or_multi_chunk_size(s, k, w, OR_MULTI_BUDGET_DEVICE)
    if b > chunk:
        return jnp.concatenate(
            [
                bitwise.gather_count_tree(rm, leaves[i : i + chunk], opc[i : i + chunk])
                for i in range(0, b, chunk)
            ]
        )
    return bitwise.gather_count_tree(rm, leaves, opc)


def topn_scorer_counts(row_matrix, pos, src_stack):
    """Per-(slice, candidate) intersection counts |rm[s, pos[k]] & src[s]|
    in one dispatch (int32[S, K]) — TopN candidate scoring across every
    slice at once.  Pallas on TPU; jnp per-slice fallback elsewhere (the
    fallback's whole-gather transient is bounded by looping slices)."""
    from pilosa_tpu.ops.pallas_kernels import fused_gather_src_counts
    from pilosa_tpu.pilosa import OR_MULTI_BUDGET_DEVICE

    n_slices, _, w = _rm_dims(row_matrix)
    if use_pallas() and _tileable(w):
        k = pos.shape[0]
        # The kernel's HBM partial-tile output is k * S * 4096 bytes
        # (summed on the XLA side), so the per-dispatch candidate chunk
        # must shrink with the slice count — a fixed k-chunk at
        # thousand-slice shapes would materialize a multi-GB transient
        # (the round-2 OOM class).
        chunk = max(1, min(
            _GATHER_BATCH_MAX,
            OR_MULTI_BUDGET_DEVICE // max(1, n_slices * 8 * 128 * 4),
        ))
        if k > chunk:
            # Pad the ragged tail to the chunk size (pad scores are
            # sliced off) so every dispatch shares ONE jitted shape.
            if k % chunk:
                pad = chunk - (k % chunk)
                pos = jnp.concatenate([pos, jnp.broadcast_to(pos[:1], (pad,))])
            out = jnp.concatenate(
                [
                    fused_gather_src_counts(
                        row_matrix, pos[i : i + chunk], src_stack
                    )
                    for i in range(0, pos.shape[0], chunk)
                ],
                axis=1,
            )
            return out[:, :k]
        return fused_gather_src_counts(row_matrix, pos, src_stack)
    rm = _rm3(row_matrix)
    if src_stack.ndim == 3:
        src_stack = src_stack.reshape(n_slices, -1)
    outs = [
        jnp.sum(
            jax.lax.population_count(
                jnp.take(rm[s], pos, axis=0) & src_stack[s][None]
            ).astype(jnp.int32),
            axis=-1,
        )
        for s in range(n_slices)
    ]
    return jnp.stack(outs)


def batch_intersection_count(rows, src, tiled: bool = False):
    """|rows[k] & src| for a stack of rows — TopN's exact-count hot loop.

    On TPU this streams the single src block through the fused Pallas
    kernel (no K-way broadcast in HBM).  ``tiled=True``: rows/src carry
    the word axis as trailing [W/128, 128] dims (rows sliced from a 4D
    engine matrix — no relayout on the way in).
    """
    if tiled:
        if use_pallas() and _tileable(rows.shape[-2] * rows.shape[-1]):
            return fused_count2("and", rows, src, tiled=True)
        rows = rows.reshape(*rows.shape[:-2], -1)
        src = src.reshape(*src.shape[:-2], -1)
        return bitwise.batch_intersection_count(rows, src)
    if use_pallas() and rows.ndim >= 2 and _tileable(rows.shape[-1]):
        return fused_count2("and", rows, src)
    return bitwise.batch_intersection_count(rows, src)
