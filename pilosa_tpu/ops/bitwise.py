"""Dense packed-bitmap ops in jnp (XLA), plus numpy host helpers.

Layout: a slice of a row is a dense bit vector of SLICE_WIDTH (2^20) bits,
packed little-endian-within-word into 32768 ``uint32`` words (bit ``i`` of
the slice lives at ``words[i >> 5] >> (i & 31) & 1``).  A fragment's working
set on device is ``uint32[rows, 32768]``; batched query execution stacks
slices into ``uint32[n_slices, 32768]``.

Reference analogs:
- ``bit_and``/``bit_or``/``bit_xor``/``bit_andnot`` — the container set-op
  kernels (roaring/roaring.go:1192-1558), dense case.
- ``count_and``/``count_or``/``count_xor``/``count_andnot`` — the fused
  popcount SIMD loops ``popcntAndSliceAsm`` etc.
  (roaring/assembly_amd64.s:25-115).  XLA fuses the elementwise op,
  ``population_count`` and the sum into a single pass over HBM, which is the
  TPU-native equivalent of the hand-scheduled asm loop.
- ``batch_intersection_count`` — the TopN ``Src.IntersectionCount`` hot loop
  (fragment.go:553-560): counts |row_k & src| for a whole stack of candidate
  rows in one batched kernel instead of a per-row scalar loop.

Counts are returned as int32 on device (a slice holds at most 2^20 bits so
per-slice counts can never overflow); cross-slice/cross-device totals are
accumulated host-side in Python ints (arbitrary precision), or as int64
equivalents via two-level reductions in the sharded path.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from pilosa_tpu.pilosa import SLICE_WIDTH

WORD_BITS = 32
WORDS_PER_SLICE = SLICE_WIDTH // WORD_BITS  # 32768


# ---------------------------------------------------------------------------
# Elementwise set algebra (jit-friendly; shapes [..., W])
# ---------------------------------------------------------------------------

def bit_and(a, b):
    return jnp.bitwise_and(a, b)


def bit_or(a, b):
    return jnp.bitwise_or(a, b)


def bit_xor(a, b):
    return jnp.bitwise_xor(a, b)


def bit_andnot(a, b):
    """a &^ b — bits in a that are not in b (Difference)."""
    return jnp.bitwise_and(a, jnp.bitwise_not(b))


# Operator-based pair-op table: works on jnp arrays AND inside Pallas
# kernel bodies (tracers lower &,|,^,~ to the bitwise ops).  Owned here so
# the jnp fallback never depends on the Pallas modules.
_PAIR_OPS = {
    "and": lambda a, b: a & b,
    "or": lambda a, b: a | b,
    "xor": lambda a, b: a ^ b,
    "andnot": lambda a, b: a & ~b,
}


def apply_pair_op(op: str, a, b):
    try:
        f = _PAIR_OPS[op]
    except KeyError:
        raise ValueError(f"unknown op {op!r}") from None
    return f(a, b)


def popcount_words(x):
    """Per-word popcount (the POPCNTQ analog, vectorized over all words)."""
    return lax.population_count(x)


# ---------------------------------------------------------------------------
# Fused op + popcount + reduce (the popcnt*Slice asm analogs)
# ---------------------------------------------------------------------------

def count(x):
    """Total set bits over the last axis. [..., W] -> [...] int32."""
    return jnp.sum(lax.population_count(x).astype(jnp.int32), axis=-1)


def count_and(a, b):
    """sum(popcount(a & b)) — IntersectionCount (popcntAndSliceAsm analog)."""
    return count(jnp.bitwise_and(a, b))


def count_or(a, b):
    return count(jnp.bitwise_or(a, b))


def count_xor(a, b):
    return count(jnp.bitwise_xor(a, b))


def count_andnot(a, b):
    return count(bit_andnot(a, b))


def batch_intersection_count(rows, src):
    """|rows[k] & src| for a stack of rows.

    rows: uint32[K, W]; src: uint32[W] (or broadcastable). Returns int32[K].
    Used by TopN's exact-count phase (fragment.go:553-560 analog) — one
    batched VPU pass instead of K scalar loops.
    """
    return count(jnp.bitwise_and(rows, src[..., None, :] if src.ndim == rows.ndim - 1 else src))


def gather_count_and(row_matrix, pairs):
    """Batched Count(Intersect(Bitmap(p0), Bitmap(p1))) over all slices.

    row_matrix: uint32[n_slices, n_rows, W]; pairs: int32[B, 2].
    Returns int32[B]: per-query counts summed over slices and words.
    XLA form of the fused gather kernel (gather → AND → popcount → reduce);
    the Pallas version in pallas_kernels.fused_gather_count2 avoids
    materializing the gathered stacks.
    """
    return gather_count("and", row_matrix, pairs)


def gather_count(op: str, row_matrix, pairs):
    """Batched Count(<op>(Bitmap(p0), Bitmap(p1))) over all slices — the
    generalization of :func:`gather_count_and` to Union ("or"),
    Difference ("andnot"), and Xor ("xor")."""
    if row_matrix.ndim == 4:  # tiled engine form: flatten the word axis
        row_matrix = row_matrix.reshape(*row_matrix.shape[:2], -1)
    a = jnp.take(row_matrix, pairs[:, 0], axis=1)  # [n_slices, B, W]
    b = jnp.take(row_matrix, pairs[:, 1], axis=1)
    return jnp.sum(lax.population_count(apply_pair_op(op, a, b)).astype(jnp.int32), axis=(0, 2))


def gather_count_multi(op: str, row_matrix, idx):
    """Batched Count over a left-fold of K gathered rows per query —
    N-operand Intersect ("and"), Union ("or"), Difference ("andnot"),
    and the time-quantum Range view cover (op="or"; time.go:95-167 +
    executor.go:498-554: a Range unions the minimal cover, then Count
    popcounts it).

    row_matrix: uint32[n_slices, n_rows, W]; idx: int32[B, K] row ids,
    short operand lists padded with a fold-idempotent id (and/or: any
    operand repeated; andnot: any non-first operand).  Returns int32[B]
    summed over slices.  XLA form (gather → reduce → popcount); the
    Pallas version streams one row per grid step without materializing
    the gather.
    """
    if row_matrix.ndim == 4:  # tiled engine form: flatten the word axis
        row_matrix = row_matrix.reshape(*row_matrix.shape[:2], -1)
    g = jnp.take(row_matrix, idx, axis=1)  # [n_slices, B, K, W]
    if op == "or":
        acc = lax.reduce(g, np.uint32(0), lax.bitwise_or, (2,))
    elif op == "and":
        acc = lax.reduce(g, np.uint32(0xFFFFFFFF), lax.bitwise_and, (2,))
    elif op == "andnot":
        # a &~ b &~ c … = a & ~(b | c | …)
        rest = lax.reduce(g[:, :, 1:], np.uint32(0), lax.bitwise_or, (2,))
        acc = jnp.bitwise_and(g[:, :, 0], jnp.bitwise_not(rest))
    else:
        raise ValueError(f"unsupported multi-op {op!r}")
    return jnp.sum(lax.population_count(acc).astype(jnp.int32), axis=(0, 2))


def gather_count_or_multi(row_matrix, idx):
    """OR-fold convenience wrapper (the fused Range cover count)."""
    return gather_count_multi("or", row_matrix, idx)


# ---------------------------------------------------------------------------
# Tree-fold counts: one dispatch for ARBITRARY nested Count trees
# (executor.go:261-276's uniform any-depth evaluation, fused)
# ---------------------------------------------------------------------------
#
# A query's boolean expression tree over Bitmap leaves is compiled to a
# PERFECT binary tree of depth D: ``leaves`` holds the 2^D gathered row
# ids (in-order), ``opc`` holds the 2^D - 1 internal-node opcodes
# level-major BOTTOM-UP (the 2^(D-1) leaf-pair nodes first, the root
# last; nodes left-to-right within a level).  Opcodes 0-3 are the pair
# ops in PQL_PAIR_OPS order (and/or/xor/andnot); TREE_PASS takes the
# LEFT child unchanged — the padding op that lets any tree shape (odd
# arities, unbalanced nesting, multi-operand Xor) fill a perfect tree.

TREE_PASS = 4


def tree_select(o, a, b):
    """Combine one node's children by opcode — elementwise over packed
    words.  Works on numpy arrays, jnp arrays, AND inside Pallas kernel
    bodies (o scalar there; array-shaped o broadcasts)."""
    if isinstance(o, np.ndarray):
        w = np.where
    else:
        w = jnp.where
    return w(
        o == 0, a & b,
        w(o == 1, a | b, w(o == 2, a ^ b, w(o == 3, a & ~b, a))),
    )


def gather_count_tree(row_matrix, leaves, opc):
    """Batched ``Count(<tree>)`` over all slices in one computation.

    row_matrix: uint32[S, R, W] (or tiled 4D); leaves: int32[B, K] with
    K = 2^D; opc: int32[B, K-1] level-major bottom-up.  Returns int32[B].
    XLA form (gather → level folds → popcount); the Pallas version
    (fused_gather_count_tree) streams one row per grid step instead of
    materializing the [S, B, K, W] gather.
    """
    if row_matrix.ndim == 4:  # tiled engine form: flatten the word axis
        row_matrix = row_matrix.reshape(*row_matrix.shape[:2], -1)
    k = leaves.shape[1]
    vals = jnp.take(row_matrix, leaves, axis=1)  # [S, B, K, W]
    off = 0
    n = k // 2
    while n >= 1:
        o = opc[None, :, off : off + n, None]  # [1, B, n, 1]
        vals = tree_select(o, vals[:, :, 0::2], vals[:, :, 1::2])
        off += n
        n //= 2
    acc = vals[:, :, 0]
    return jnp.sum(lax.population_count(acc).astype(jnp.int32), axis=(0, 2))


def np_gather_count_tree(
    row_matrix: np.ndarray, leaves: np.ndarray, opc: np.ndarray
) -> np.ndarray:
    """numpy ground truth for gather_count_tree."""
    k = leaves.shape[1]
    vals = row_matrix[:, leaves, :]  # [S, B, K, W]
    off = 0
    n = k // 2
    while n >= 1:
        o = opc[None, :, off : off + n, None]
        vals = tree_select(o, vals[:, :, 0::2], vals[:, :, 1::2])
        off += n
        n //= 2
    acc = vals[:, :, 0]
    return np_popcount(acc).reshape(acc.shape[0], acc.shape[1], -1).sum(axis=(0, 2))


def np_gather_count_multi(op: str, row_matrix: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """numpy ground truth for gather_count_multi."""
    g = row_matrix[:, idx, :]  # [S, B, K, W]
    if op == "or":
        acc = np.bitwise_or.reduce(g, axis=2)
    elif op == "and":
        acc = np.bitwise_and.reduce(g, axis=2)
    elif op == "andnot":
        acc = g[:, :, 0] & ~np.bitwise_or.reduce(g[:, :, 1:], axis=2)
    else:
        raise ValueError(f"unsupported multi-op {op!r}")
    return np_popcount(acc).reshape(acc.shape[0], acc.shape[1], -1).sum(axis=(0, 2))


def np_gather_count_or_multi(row_matrix: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """numpy ground truth for gather_count_or_multi."""
    return np_gather_count_multi("or", row_matrix, idx)


# One-shot Gram unpack budget: past this, the int8 bit matrix streams
# chunk-by-chunk through the MXU instead (pair_gram's scan path).
GRAM_ONESHOT_BYTES = 1536 * 1024 * 1024

# Per-step unpack budget for the streamed builder.  A step's live int8
# bits are R * chunk_words * 32 bytes; tall row sets (4k+ rows, where a
# single slice's unpack would be 4+ GB) subdivide the word axis until a
# step fits, so the builder has NO row-count ceiling — only the Gram
# matrix itself (R^2 ints) and the int32 count bound gate it (callers).
GRAM_STEP_BYTES = 768 * 1024 * 1024


def pair_gram(row_matrix):
    """All-pairs intersection-count Gram matrix G[i,j] = |row_i & row_j|
    summed over slices, on the MXU.

    The MXU strategy for cacheable working sets: slices are disjoint bit
    ranges of the same rows, so the Gram over the concatenated unpacked
    bit vectors equals the per-slice sum — and any word-axis subdivision
    of a slice splits it further into disjoint bit ranges, so the same
    identity lets one step carry an arbitrarily small column chunk.
    int8×int8→int32 accumulation is exact (products are 0/1; per-pair
    counts are ≤ S * 2^20, so int32 holds up to 2047 slices — gate at
    the caller).  G answers every pair op through count identities (see
    gram_pair_counts), and — being a pure function of the row matrix —
    XLA hoists it out of query-stream loops, so a stream of fused
    batches pays for it once.

    Small matrices unpack once and do ONE matmul; large ones (a 1024-
    slice x 64-row matrix is 8 GB packed = 64 GB unpacked) scan
    (slice, word-chunk) steps, accumulating ``G += bits @ bits.T`` with
    only one chunk's int8 bits (R * chunk_words * 32 bytes, bounded by
    GRAM_STEP_BYTES) live per step — billion-column indexes AND
    thousand-row working sets get all-pairs answers for one streamed
    pass of MXU work.
    """
    if row_matrix.ndim == 4:  # tiled engine form (word order is identical)
        s, r = row_matrix.shape[:2]
        w = row_matrix.shape[2] * row_matrix.shape[3]
    else:
        s, r, w = row_matrix.shape
    shifts = jnp.arange(32, dtype=jnp.uint32)

    def unpack2(x):  # [r, ...words] -> int8 [r, words*32]
        b = ((x[..., None] >> shifts) & jnp.uint32(1)).astype(jnp.int8)
        return b.reshape(x.shape[0], -1)

    if s * r * w * 32 <= GRAM_ONESHOT_BYTES:
        if row_matrix.ndim == 4:
            row_matrix = row_matrix.reshape(s, r, w)
        flat = row_matrix.transpose(1, 0, 2).reshape(r, s * w)
        bits = unpack2(flat)
        return lax.dot_general(
            bits, bits, (((1,), (1,)), ((), ())), preferred_element_type=jnp.int32
        )

    # Word-axis subdivision: split each slice into nc equal chunks (nc a
    # power-of-two divisor of the chunkable axis) until a step's unpack
    # fits the budget.  nc=1 reproduces the per-slice scan exactly.
    chunk_axis = row_matrix.shape[2]  # 4D: tile count; 3D: words
    nc = 1
    while (
        r * (w // nc) * 32 > GRAM_STEP_BYTES
        and nc * 2 <= chunk_axis
        and chunk_axis % (nc * 2) == 0
    ):
        nc *= 2

    def step(acc, i):
        # One (slice, chunk) per step, fetched by index: scanning rm's
        # leading axis directly (or reshaping the unpacked bits) made
        # XLA relayout the whole CARRIED matrix into an MXU-friendly
        # transposed tiling — an 8 GB HLO-temp copy at the 1024-slice
        # shape.  Indexed access keeps the matrix in its born layout;
        # only the per-step chunk gets copied/transposed.
        if nc == 1:
            sl = lax.dynamic_index_in_dim(row_matrix, i, 0, keepdims=False)
        else:
            si, ci = i // nc, i % nc
            cw = chunk_axis // nc
            if row_matrix.ndim == 4:
                sl = lax.dynamic_slice(
                    row_matrix,
                    (si, 0, ci * cw, 0),
                    (1, r, cw, row_matrix.shape[3]),
                )[0]
            else:
                sl = lax.dynamic_slice(
                    row_matrix, (si, 0, ci * cw), (1, r, cw)
                )[0]
        # The barrier stops the MXU's layout preference from propagating
        # through the slice to the carried matrix (verified: without it
        # XLA still inserts the full transposed copy).
        sl = lax.optimization_barrier(sl)
        b = ((sl[..., None] >> shifts) & jnp.uint32(1)).astype(jnp.int8)
        dims = tuple(range(1, b.ndim))
        return acc + lax.dot_general(
            b, b, ((dims, dims), ((), ())), preferred_element_type=jnp.int32
        ), None

    return lax.scan(step, jnp.zeros((r, r), jnp.int32), jnp.arange(s * nc))[0]


def gram_pair_counts(op: str, gram, pairs):
    """Per-pair counts for any pair op from the AND-Gram matrix.

    |a|b| = |a|+|b|-|a&b|;  |a^b| = |a|+|b|-2|a&b|;  |a&~b| = |a|-|a&b|.
    Works on numpy or jnp arrays (gram: int32[R,R]; pairs: int[B,2]).
    """
    g_and = gram[pairs[:, 0], pairs[:, 1]]
    if op == "and":
        return g_and
    d0 = gram[pairs[:, 0], pairs[:, 0]]
    d1 = gram[pairs[:, 1], pairs[:, 1]]
    if op == "or":
        return d0 + d1 - g_and
    if op == "xor":
        return d0 + d1 - 2 * g_and
    if op == "andnot":
        return d0 - g_and
    raise ValueError(f"unknown op {op!r}")


# ---------------------------------------------------------------------------
# Host-side numpy helpers (mask building, packing) — used to prepare
# device inputs; never inside jit (they produce constants).
# ---------------------------------------------------------------------------

def make_range_mask(start_bit: int, end_bit: int, n_words: int = WORDS_PER_SLICE) -> np.ndarray:
    """Dense uint32 mask with bits [start_bit, end_bit) set.

    Used for Range/CountRange style queries restricted to a column interval
    within a slice (roaring.go CountRange analog), and to mask the tail of a
    partially-filled last slice.
    """
    start_bit = max(0, min(start_bit, n_words * WORD_BITS))
    end_bit = max(start_bit, min(end_bit, n_words * WORD_BITS))
    mask = np.zeros(n_words, dtype=np.uint32)
    if start_bit == end_bit:
        return mask
    sw, sb = divmod(start_bit, WORD_BITS)
    ew, eb = divmod(end_bit, WORD_BITS)
    if sw == ew:
        mask[sw] = ((np.uint64(1) << np.uint64(eb)) - np.uint64(1)) & ~(
            (np.uint64(1) << np.uint64(sb)) - np.uint64(1)
        )
        return mask
    mask[sw] = np.uint32(0xFFFFFFFF) & np.uint32(~((1 << sb) - 1) & 0xFFFFFFFF)
    mask[sw + 1 : ew] = np.uint32(0xFFFFFFFF)
    if ew < n_words and eb:
        mask[ew] = np.uint32((1 << eb) - 1)
    return mask


def pack_positions(positions: np.ndarray, n_words: int = WORDS_PER_SLICE) -> np.ndarray:
    """Pack sorted (or unsorted) bit positions into a dense uint32 word array."""
    words = np.zeros(n_words, dtype=np.uint32)
    if len(positions) == 0:
        return words
    positions = np.asarray(positions, dtype=np.uint64)
    w = (positions >> np.uint64(5)).astype(np.int64)
    b = (positions & np.uint64(31)).astype(np.uint32)
    np.bitwise_or.at(words, w, np.uint32(1) << b)
    return words


def unpack_positions(words: np.ndarray) -> np.ndarray:
    """Inverse of pack_positions: dense words -> sorted uint64 bit positions."""
    words = np.ascontiguousarray(words, dtype=np.uint32)
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint64)


def pack_rows_matrix(rows_positions, n_rows: int, n_words: int = WORDS_PER_SLICE) -> np.ndarray:
    """Build a dense uint32[n_rows, n_words] matrix from per-row position lists."""
    m = np.zeros((n_rows, n_words), dtype=np.uint32)
    for r, pos in rows_positions:
        if r < n_rows and len(pos):
            m[r] = pack_positions(pos, n_words)
    return m


# ---------------------------------------------------------------------------
# numpy reference implementations (ground truth for property tests — the
# analog of the Go SWAR fallbacks in roaring/assembly.go:26-73)
# ---------------------------------------------------------------------------

def np_popcount(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.uint32)
    return np.unpackbits(x.view(np.uint8)).reshape(*x.shape, 32).sum(-1)


# Byte-popcount lookup table for count_words: one gather + sum beats the
# 8x unpackbits expansion by ~20x when only the TOTAL is wanted.
_POP8 = np_popcount(np.arange(256, dtype=np.uint32)).astype(np.uint16)


def count_words(x: np.ndarray) -> int:
    """Total set-bit count of a packed word array (any uint dtype).
    The fast lane for cardinality-only callers — np_popcount stays the
    per-word reference (property tests hold this to it)."""
    x = np.ascontiguousarray(x)
    return int(_POP8[x.view(np.uint8)].sum(dtype=np.int64))


def np_count(x: np.ndarray) -> int:
    return int(np_popcount(x).sum())


def np_count_and(a, b) -> int:
    return np_count(np.bitwise_and(a, b))


def np_count_or(a, b) -> int:
    return np_count(np.bitwise_or(a, b))


def np_count_xor(a, b) -> int:
    return np_count(np.bitwise_xor(a, b))


def np_count_andnot(a, b) -> int:
    return np_count(np.bitwise_and(a, np.bitwise_not(np.asarray(b, dtype=np.uint32))))
