"""Differential fuzz harness: every kernel/strategy lane vs numpy.

The reference's single most important test idiom is asm-vs-Go
equivalence over randomized inputs (roaring/assembly_test.go:45-141).
This module is that idiom generalized to the full lane surface of this
build: for each strategy lane (fused count, resident, slice-major
gather, row-major gather, multi-fold both layouts, TopN scorer, Gram
one-shot/scan/word-chunked, dispatch 3D/4D parity) it generates N
random (shape, op, density, layout) cases and requires EXACT agreement
with a pure-numpy ground truth.

Two consumers run the same cases:
- the pytest suite (tests/test_differential_kernels.py), CPU backend,
  Pallas kernels in interpret mode;
- ``tpu_selftest.py`` on a real chip, the actual Mosaic lowering.
"""

from __future__ import annotations

import numpy as np

# Fixed shape buckets bound jit recompiles (each distinct shape traces
# once; values/ops/densities vary freely inside a bucket).
# Words must satisfy ops.pallas_kernels._tileable (divisible by 8*128).
SHAPES = [  # (n_slices, n_rows, words)
    (1, 8, 1024),
    (2, 16, 2048),
    (3, 48, 1024),
    (2, 64, 3072),
]
B = 16  # queries per case
KS = (2, 4)  # multi-fold operand buckets
PAIR_OPS = ("and", "or", "xor", "andnot")
MULTI_OPS = ("and", "or", "andnot")


def _random_words(rng: np.random.Generator, shape, density_k: int) -> np.ndarray:
    """uint32 words with controlled bit density: AND of k draws ~ 2^-k
    density, OR of k draws ~ 1 - 2^-k; k=0 -> all zeros, k=-1 -> all ones.
    Extreme densities are where popcount accumulators and fold-identity
    padding break."""
    if density_k == 0:
        return np.zeros(shape, dtype=np.uint32)
    if density_k == -1:
        return np.full(shape, 0xFFFFFFFF, dtype=np.uint32)
    out = rng.integers(0, 1 << 32, size=shape, dtype=np.uint32)
    for _ in range(abs(density_k) - 1):
        nxt = rng.integers(0, 1 << 32, size=shape, dtype=np.uint32)
        out = (out & nxt) if density_k > 0 else (out | nxt)
    return out


_DENSITIES = (1, 3, -3, 0, -1)  # ~0.5, ~0.125, ~0.875, zeros, ones


def gen_case(rng: np.random.Generator, shape):
    """One random case for a shape bucket."""
    s, r, w = shape
    dk = int(rng.choice(_DENSITIES))
    rm = _random_words(rng, (s, r, w), dk)
    pairs = rng.integers(0, r, size=(B, 2), dtype=np.int32)
    idx = {k: rng.integers(0, r, size=(B, k), dtype=np.int32) for k in KS}
    src = _random_words(rng, (s, w), 1)
    return rm, pairs, idx, src


# ---- numpy ground truths ---------------------------------------------------

def _np_pop(x: np.ndarray) -> np.ndarray:
    from pilosa_tpu.ops.bitwise import np_popcount

    return np_popcount(x)


def _np_pair(op: str, a: np.ndarray, b: np.ndarray) -> int:
    from pilosa_tpu.ops import bitwise as bw

    fn = {
        "and": bw.np_count_and,
        "or": bw.np_count_or,
        "xor": bw.np_count_xor,
        "andnot": bw.np_count_andnot,
    }[op]
    return int(fn(a, b))


def np_pair_counts(op: str, rm: np.ndarray, pairs: np.ndarray) -> list[int]:
    return [
        sum(_np_pair(op, rm[s, int(p0)], rm[s, int(p1)]) for s in range(rm.shape[0]))
        for p0, p1 in pairs
    ]


def np_multi_counts(op: str, rm: np.ndarray, idx: np.ndarray) -> list[int]:
    from pilosa_tpu.ops.bitwise import np_gather_count_multi

    return [int(v) for v in np_gather_count_multi(op, rm, idx)]


def np_topn_counts(rm: np.ndarray, src: np.ndarray) -> list[int]:
    return [
        int(_np_pop(rm[:, ri, :] & src).sum()) for ri in range(rm.shape[1])
    ]


def np_gram(rm: np.ndarray) -> np.ndarray:
    r = rm.shape[1]
    out = np.zeros((r, r), dtype=np.int64)
    for i in range(r):
        for j in range(r):
            out[i, j] = sum(
                _np_pop(rm[s, i] & rm[s, j]).sum() for s in range(rm.shape[0])
            )
    return out


# ---- lane runners ----------------------------------------------------------

def run_lanes(seed: int, cases_per_lane: int, interpret: bool) -> list[str]:
    """Run every lane over generated cases; returns failure descriptions
    (empty = all lanes agree with numpy everywhere)."""
    import jax
    import jax.numpy as jnp

    from pilosa_tpu.ops import bitwise as bw
    from pilosa_tpu.ops import dispatch
    from pilosa_tpu.ops import pallas_kernels as pk

    failures: list[str] = []
    rng = np.random.default_rng(seed)

    def check(lane: str, case_i: int, got, want) -> None:
        got = np.asarray(got).astype(np.int64).reshape(-1).tolist()
        want = list(want) if isinstance(want, (list, tuple)) else [want]
        if got[: len(want)] != want:
            failures.append(
                f"{lane}[case {case_i}]: got {got[:len(want)][:6]}... want {want[:6]}..."
            )

    for ci in range(cases_per_lane):
        shape = SHAPES[ci % len(SHAPES)]
        s, r, w = shape
        rm, pairs, idx, src = gen_case(rng, shape)
        rm4 = jnp.asarray(rm.reshape(s, r, w // 128, 128))
        rmj = jnp.asarray(rm)
        rmt = np.ascontiguousarray(rm.transpose(1, 0, 2))
        rmt4 = jnp.asarray(rmt.reshape(r, s, w // 128, 128))
        # Decorrelated from the shape cycle (len(SHAPES)=4 would alias a
        # same-period op cycle: each op pinned to one shape forever) —
        # rng draws give every (op, k, shape) combination coverage across
        # cases while shapes still cycle deterministically for jit reuse.
        op = PAIR_OPS[int(rng.integers(len(PAIR_OPS)))]
        mop = MULTI_OPS[int(rng.integers(len(MULTI_OPS)))]
        k = KS[int(rng.integers(len(KS)))]

        # L0 whole-array counts (popcntSliceAsm / popcnt*SliceAsm
        # analogs).  These kernels return (8, 128) PARTIAL TILES per row
        # (scalar outputs can't lower on TPU); callers reduce — mirror
        # that contract here.
        a2, b2 = rm[0], rm[(s - 1) % s]
        check("count1", ci,
              np.asarray(pk.fused_count1(jnp.asarray(a2), interpret=interpret)).sum(),
              int(_np_pop(a2).sum()))
        check(f"count2:{op}", ci,
              np.asarray(pk.fused_count2(
                  op, jnp.asarray(a2), jnp.asarray(b2), interpret=interpret)).sum(),
              _np_pair(op, a2, b2))
        # tiled (4D) form of the same pair
        check(f"count2_tiled:{op}", ci,
              np.asarray(pk.fused_count2(
                  op, jnp.asarray(a2.reshape(r, w // 128, 128)),
                  jnp.asarray(b2.reshape(r, w // 128, 128)),
                  interpret=interpret, tiled=True)).sum(),
              _np_pair(op, a2, b2))

        want_pairs = np_pair_counts(op, rm, pairs)
        jp = jnp.asarray(pairs)
        # resident lane (stream-all-rows strategy)
        check(f"resident:{op}", ci,
              pk.fused_resident_count2(op, rm4, jp, interpret=interpret), want_pairs)
        # slice-major gather lane
        check(f"gather:{op}", ci,
              pk.fused_gather_count2(op, rm4, jp, interpret=interpret), want_pairs)
        # row-major gather lane (one contiguous descriptor per operand row)
        check(f"rmgather:{op}", ci,
              pk.fused_gather_count2_rowmajor(op, rmt4, jp, interpret=interpret),
              want_pairs)
        # multi-fold lanes, both layouts
        want_multi = np_multi_counts(mop, rm, idx[k])
        ji = jnp.asarray(idx[k])
        check(f"multi:{mop}:k{k}", ci,
              pk.fused_gather_count_multi(mop, rm4, ji, interpret=interpret), want_multi)
        check(f"rmmulti:{mop}:k{k}", ci,
              pk.fused_gather_count_multi_rowmajor(mop, rmt4, ji, interpret=interpret),
              want_multi)
        # TopN candidate scorer
        check("topn", ci,
              pk.fused_topn_counts(rm4, jnp.asarray(src), interpret=interpret),
              np_topn_counts(rm, src))

        # Gram lanes: one-shot, forced scan (per slice), forced word-chunk
        want_gram = np_gram(rm)
        got_one = np.asarray(bw.pair_gram(rmj)).astype(np.int64)
        orig_oneshot, orig_step = bw.GRAM_ONESHOT_BYTES, bw.GRAM_STEP_BYTES
        try:
            bw.GRAM_ONESHOT_BYTES = 1
            got_scan = np.asarray(bw.pair_gram(rm4)).astype(np.int64)
            bw.GRAM_STEP_BYTES = r * (w // 4) * 32
            got_chunk = np.asarray(bw.pair_gram(rm4)).astype(np.int64)
        finally:
            bw.GRAM_ONESHOT_BYTES, bw.GRAM_STEP_BYTES = orig_oneshot, orig_step
        for lane, got_g in (("gram_oneshot", got_one), ("gram_scan", got_scan),
                            ("gram_chunked", got_chunk)):
            if not np.array_equal(got_g, want_gram):
                failures.append(f"{lane}[case {ci}]: gram mismatch")
        # Gram count identities answer every pair op
        check(f"gram_pairs:{op}", ci,
              np.asarray(bw.gram_pair_counts(op, want_gram, pairs)), want_pairs)

        # dispatch-level parity: 3D vs 4D vs numpy, current backend's
        # chosen lane (Pallas on TPU, jnp on CPU CI)
        check(f"dispatch:{op}", ci,
              dispatch.gather_count(op, rmj, jp, allow_gram=False), want_pairs)
        check(f"dispatch4:{op}", ci,
              dispatch.gather_count(op, rm4, jp, allow_gram=False), want_pairs)
        check(f"dispatch_gram:{op}", ci,
              dispatch.gather_count(op, rmj, jp, allow_gram=True), want_pairs)
        check(f"dispatch_multi:{mop}", ci,
              dispatch.gather_count_multi(mop, rm4, ji), want_multi)

    return failures


def lane_names() -> set[str]:
    """The lane identifiers run_lanes covers (for coverage assertions)."""
    lanes = {"count1", "topn", "gram_oneshot", "gram_scan", "gram_chunked"}
    for op in PAIR_OPS:
        lanes |= {f"count2:{op}", f"count2_tiled:{op}", f"resident:{op}",
                  f"gather:{op}", f"rmgather:{op}", f"gram_pairs:{op}",
                  f"dispatch:{op}", f"dispatch4:{op}", f"dispatch_gram:{op}"}
    for mop in MULTI_OPS:
        for k in KS:
            lanes |= {f"multi:{mop}:k{k}", f"rmmulti:{mop}:k{k}"}
        lanes.add(f"dispatch_multi:{mop}")
    return lanes
