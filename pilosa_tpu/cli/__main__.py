"""`python -m pilosa_tpu.cli` entry point (same CLI as `python -m pilosa_tpu`)."""

import sys

from pilosa_tpu.cli.main import main

sys.exit(main())
