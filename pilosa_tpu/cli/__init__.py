"""Command-line interface.

Reference analog: cmd/ (cobra root, cmd/root.go:36-78) + ctl/ tools.
Subcommands: server, backup, restore, import, export, bench, check,
inspect, sort, config — invoked as ``python -m pilosa_tpu <cmd>``.
"""

from pilosa_tpu.cli.main import main  # noqa: F401
