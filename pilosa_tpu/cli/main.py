"""CLI subcommands (reference: cmd/*.go + ctl/*.go).

Config precedence matches cmd/root.go:89-153: flags > PILOSA_* env >
TOML config file > defaults.
"""

from __future__ import annotations

import argparse
import io
import json
import sys
import tarfile
import time

import numpy as np


def _load_config(args) -> "Config":
    from pilosa_tpu.config import Config

    cfg = Config.from_toml(args.config) if getattr(args, "config", None) else Config()
    cfg.apply_env()
    # flags override
    if getattr(args, "data_dir", None):
        cfg.data_dir = args.data_dir
    if getattr(args, "host", None):
        cfg.host = args.host
    return cfg


# -- server (cmd/server.go) -------------------------------------------------

def _spawn_reuseport_workers(cfg, server, args) -> list:
    """[server] workers > 1: the multi-core fallback for GIL builds.

    The parent has already bound with SO_REUSEPORT (Server.open turns
    it on when workers > 1); N-1 sibling server processes bind the same
    resolved port and the kernel spreads accepted connections across
    them.  On a free-threaded build (GIL disabled) the in-process
    worker pool already serves N cores, so nothing is forked.  Each
    sibling is a full server over the same data-dir: read-path scaling
    only — route writes through the replica router (DEVELOPMENT.md
    "Multi-core serving") when multi-process write consistency matters.
    """
    import os
    import subprocess

    n = int(getattr(cfg, "server_workers", 0) or 0)
    if n <= 1 or os.environ.get("PILOSA_TPU_SERVER_WORKER_CHILD") == "1":
        return []
    gil_enabled = getattr(sys, "_is_gil_enabled", lambda: True)()
    if not gil_enabled:
        print(f"free-threaded build: {n} workers collapse into the in-process pool")
        return []
    env = dict(os.environ)
    env["PILOSA_TPU_SERVER_WORKER_CHILD"] = "1"
    env["PILOSA_HOST"] = server.host  # the parent's RESOLVED host:port
    env["PILOSA_TPU_SERVER_WORKERS"] = str(n)  # keeps SO_REUSEPORT on
    env["PILOSA_DATA_DIR"] = server.data_dir
    cmd = [sys.executable, "-m", "pilosa_tpu", "server"]
    if getattr(args, "config", None):
        cmd += ["--config", args.config]
    procs = [subprocess.Popen(cmd, env=env) for _ in range(n - 1)]
    print(f"spawned {len(procs)} SO_REUSEPORT worker processes on {server.host}")
    return procs


def cmd_server(args) -> int:
    from pilosa_tpu.server.server import Server

    cfg = _load_config(args)
    profiler = None
    if getattr(args, "profile_cpu", None):
        # cmd/server.go:100 parity: profile the whole serving lifetime,
        # written on shutdown (pstats; inspect with `python -m pstats`).
        # On CPython 3.12+ cProfile rides sys.monitoring, whose events
        # are process-global, so one enable() here captures the
        # thread-per-request HTTP handler threads too (goroutine-wide
        # sampling parity with Go's pprof; verified empirically — a
        # second per-thread Profile raises "Another profiling tool is
        # already active").
        import cProfile

        profiler = cProfile.Profile()
        profiler.enable()

    workers: list = []

    def _finish() -> None:
        for p in workers:
            p.terminate()
        for p in workers:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        server.close()
        if profiler is not None:
            profiler.disable()
            profiler.dump_stats(args.profile_cpu)
            print(f"cpu profile written to {args.profile_cpu}")

    server = Server(cfg)
    server.open()
    workers = _spawn_reuseport_workers(cfg, server, args)
    print(f"pilosa-tpu serving on http://{server.host} (data: {server.data_dir})")
    if args.test_exit:  # for CLI tests: start, report, stop
        _finish()
        return 0
    # SIGTERM (systemd/docker stop) must flush the profile and close the
    # holder exactly like Ctrl-C, not die inside time.sleep.  The handler
    # disarms itself so a second TERM/INT during shutdown cannot abort
    # close() mid-flush, and _finish runs in a finally for the same
    # reason.
    import signal

    def _on_term(signum, frame):
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _on_term)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("shutting down")
    finally:
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        signal.signal(signal.SIGINT, signal.SIG_IGN)
        _finish()
    return 0


# -- lockstep (TPU-native multi-host serving; no reference analog — the
# reference's only multi-node mode is the coordinator-style cluster) --------

def cmd_lockstep(args) -> int:
    """Serve queries SPMD-lockstep over a jax.distributed job.

    Run the SAME command on every process of the job; rank 0 serves HTTP
    and the control plane, other ranks replay.  On TPU pods omit the
    coordinator flags (topology comes from the runtime).
    """
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.parallel.multihost import init_multihost
    from pilosa_tpu.parallel.service import LockstepService

    cfg = _load_config(args)
    init_multihost(
        coordinator=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
        local_device_count=args.local_devices,
    )
    holder = Holder(cfg.data_dir, ranking_debounce_s=cfg.ranking_debounce_s)
    holder.open()
    host, _, port = cfg.host.partition(":")
    ctrl_host, _, ctrl_port = args.control.partition(":")
    # [replica] group: this job's serving-group identity behind the
    # replica router ("name" or "name@epoch"; flag > env/TOML).
    from pilosa_tpu.replica import parse_group

    gname, gepoch = parse_group(getattr(args, "group", None) or cfg.replica_group)
    svc = LockstepService(
        holder,
        control_addr=(ctrl_host or "127.0.0.1", int(ctrl_port)),
        http_addr=(host or "127.0.0.1", int(port or 10101)),
        ack_timeout=cfg.lockstep_ack_timeout,
        connect_timeout=cfg.lockstep_connect_timeout,
        queue_depth=cfg.lockstep_queue_depth,
        default_deadline_ms=cfg.default_deadline_ms,
        # [qcache] wiring: the service forces min-cost-ms to 0 itself
        # (wall-clock admission is rank-local; lockstep hit/miss must be
        # a pure function of replicated state).
        qcache_enabled=cfg.qcache_enabled,
        qcache_max_bytes=cfg.qcache_max_bytes,
        # [trace] wiring: rank 0 decides sampling at ship time and
        # records spans; workers only read the replicated wire flag.
        trace_sample_rate=cfg.trace_sample_rate,
        trace_slow_ms=cfg.trace_slow_ms,
        group=gname,
        group_epoch=gepoch,
        # [bulk] wiring: rank 0 decodes chunks, every rank rebuilds
        # planes from the replicated pairs; the budget shapes each
        # rank's lazy-materialization drain.
        bulk_batch_slices=cfg.bulk_batch_slices,
        bulk_materialize_budget_ms=cfg.bulk_materialize_budget_ms,
        # [tenancy] wiring: rank 0 resolves each request's tenant once
        # at ship time (header > this map > index name) and ships it on
        # the batch entry like the expiry/trace flags.
        tenancy_map=cfg.tenancy_map,
    )
    if svc.rank == 0:
        print(
            f"pilosa-tpu lockstep rank 0: http on {cfg.host}, "
            f"control on {args.control}, {svc.n_ranks} ranks",
            flush=True,
        )
    else:
        print(f"pilosa-tpu lockstep rank {svc.rank}: replaying from {args.control}", flush=True)
    try:
        svc.serve_forever()
    except KeyboardInterrupt:
        if svc.rank == 0:
            svc.shutdown()
    finally:
        holder.close()
    return 0


# -- replica-router (replicated serving groups; no reference analog — the
# reference's ReplicaN picks owners inside one cluster, this routes across
# whole serving groups) ------------------------------------------------------

def cmd_replica_router(args) -> int:
    """Front a set of replica serving groups: fan reads across healthy
    groups (least-inflight, one-shot failover), sequence writes to ALL
    groups in one total order.
    """
    from pilosa_tpu import trace as trace_mod
    from pilosa_tpu.replica import router_from_config
    from pilosa_tpu.stats import new_stats_client

    cfg = _load_config(args)
    if getattr(args, "groups", None):
        cfg.replica_groups = [g.strip() for g in args.groups.split(",") if g.strip()]
    if getattr(args, "port", None) is not None:
        cfg.replica_router_port = args.port
    if getattr(args, "wal_dir", None):
        cfg.replica_wal_dir = args.wal_dir
    if getattr(args, "probe_interval", None) is not None:
        cfg.replica_probe_interval = args.probe_interval
    if getattr(args, "anti_entropy_interval", None) is not None:
        cfg.replica_anti_entropy_interval = args.anti_entropy_interval
    if getattr(args, "shards", None) is not None:
        cfg.replica_shards = args.shards
    if getattr(args, "shard_map", None):
        cfg.replica_shard_map = args.shard_map
    if getattr(args, "shard_span", None) is not None:
        cfg.replica_shard_span = args.shard_span
    if cfg.replica_shard_map:
        from pilosa_tpu.replica import ShardMapError, parse_shard_map

        try:
            smap = parse_shard_map(cfg.replica_shard_map)
        except ShardMapError as e:
            print(f"error: bad --shard-map: {e}", file=sys.stderr)
            return 1
        cfg.replica_groups = [
            g for sh in smap for g in sh.group_specs
        ]
    if not cfg.replica_groups:
        print("error: no replica groups configured "
              "(--groups / [replica] groups / PILOSA_TPU_REPLICA_GROUPS)",
              file=sys.stderr)
        return 1
    if not cfg.replica_shard_map and int(cfg.replica_shards or 1) > 1:
        from pilosa_tpu.replica import ShardMapError, uniform_shard_map

        try:
            uniform_shard_map(cfg.replica_groups, int(cfg.replica_shards),
                              span=int(cfg.replica_shard_span or 1))
        except ShardMapError as e:
            print(f"error: bad --shards split: {e}", file=sys.stderr)
            return 1
    stats = new_stats_client(cfg.stats)
    router = router_from_config(
        cfg, stats=stats, tracer=trace_mod.from_config(cfg, stats=stats)
    )
    router.serve()
    wal_note = (
        f", wal: {cfg.replica_wal_dir}" if cfg.replica_wal_dir else ", wal: memory"
    )
    shard_note = (
        f" in {len(router.shards)} shards" if len(router.shards) > 1 else ""
    )
    print(
        f"pilosa-tpu replica-router on http://{router.host}:{router.port} "
        f"over {len(router.groups)} groups{shard_note}: "
        + ", ".join(f"{g.name}={g.base}" for g in router.groups)
        + wal_note,
        flush=True,
    )
    if args.test_exit:  # for CLI tests: start, report, stop
        router.close()
        return 0
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("shutting down")
    finally:
        router.close()
    return 0


# -- import/export (ctl/import.go, ctl/export.go) ---------------------------

def cmd_import(args) -> int:
    from pilosa_tpu import native
    from pilosa_tpu.server.client import Client

    client = Client(args.host)
    total = 0
    for path in args.paths:
        data = sys.stdin.buffer.read() if path == "-" else open(path, "rb").read()
        rows, cols, ts = native.parse_csv(data)
        for start in range(0, len(rows), args.buffer_size):
            end = start + args.buffer_size
            bits = list(zip(rows[start:end].tolist(), cols[start:end].tolist(), ts[start:end].tolist()))
            client.import_bits(args.index, args.frame, bits)
            total += len(bits)
    print(f"imported {total} bits into {args.index}/{args.frame}")
    return 0


def cmd_ingest(args) -> int:
    """Client half of the streaming columnar bulk-ingest door: parse
    CSV with the native parser, stream packed-uint64 chunks, resume at
    the server's staged frontier if interrupted and re-run."""
    from pilosa_tpu import native
    from pilosa_tpu.server.client import Client

    client = Client(args.host)
    total = 0
    for path in args.paths:
        data = sys.stdin.buffer.read() if path == "-" else open(path, "rb").read()
        rows, cols, _ts = native.parse_csv(data)
        client.ingest_stream(
            args.index, args.frame, rows, cols, chunk_pairs=args.chunk_pairs
        )
        total += len(rows)
    print(f"streamed {total} bits into {args.index}/{args.frame} via /ingest")
    return 0


def cmd_bulk(args) -> int:
    """Client half of the device-build bulk door: parse CSV with the
    native parser, stream chunks through POST .../bulk (packed-uint64
    framing, or Arrow IPC record batches with --arrow) — the server
    bit-packs planes on device and defers roaring materialization."""
    from pilosa_tpu import native
    from pilosa_tpu.server.client import Client

    client = Client(args.host)
    total = 0
    for path in args.paths:
        data = sys.stdin.buffer.read() if path == "-" else open(path, "rb").read()
        rows, cols, _ts = native.parse_csv(data)
        client.bulk_stream(
            args.index, args.frame, rows, cols,
            chunk_pairs=args.chunk_pairs, arrow=args.arrow,
        )
        total += len(rows)
    print(f"streamed {total} bits into {args.index}/{args.frame} via /bulk")
    return 0


def cmd_export(args) -> int:
    from pilosa_tpu.server.client import Client, ClientError

    client = Client(args.host)
    max_slice = client.max_slices().get(args.index, 0)
    if getattr(args, "format", "csv") == "arrow":
        # Arrow egress is a byte stream (one IPC stream per slice),
        # concatenated to the output; stdout gets the binary buffer.
        out = sys.stdout.buffer if args.output == "-" else open(args.output, "wb")
        try:
            for slice_i in range(max_slice + 1):
                try:
                    out.write(
                        client.export_arrow(args.index, args.frame, args.view, slice_i)
                    )
                except ClientError as e:
                    if e.status != 404:
                        raise
                    print(
                        f"warning: slice {slice_i} not on {args.host} (404); "
                        "export may be partial — run against each cluster node",
                        file=sys.stderr,
                    )
        finally:
            if out is not sys.stdout.buffer:
                out.close()
        return 0
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    try:
        for slice_i in range(max_slice + 1):
            try:
                out.write(client.export_csv(args.index, args.frame, args.view, slice_i))
            except ClientError as e:
                # Slices the local node doesn't hold 404 (sparse frames,
                # cluster peers own them); anything else is a real failure.
                if e.status != 404:
                    raise
                print(
                    f"warning: slice {slice_i} not on {args.host} (404); "
                    "export may be partial — run against each cluster node",
                    file=sys.stderr,
                )
    finally:
        if out is not sys.stdout:
            out.close()
    return 0


# -- backup/restore (ctl/backup.go, ctl/restore.go) -------------------------

def cmd_backup(args) -> int:
    from pilosa_tpu.server.client import Client

    client = Client(args.host)
    max_slice = client.max_slices().get(args.index, 0)
    views = client.frame_views(args.index, args.frame)
    with tarfile.open(args.output, "w") as tar:
        for view in views:
            for slice_i in range(max_slice + 1):
                data = client.fragment_data(args.index, args.frame, view, slice_i)
                if data is None:
                    continue
                info = tarfile.TarInfo(name=f"{view}/{slice_i}")
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
    print(f"backed up {args.index}/{args.frame} to {args.output}")
    return 0


def cmd_restore(args) -> int:
    from pilosa_tpu.server.client import Client

    client = Client(args.host)
    n = 0
    with tarfile.open(args.input) as tar:
        for member in tar.getmembers():
            view, slice_s = member.name.split("/", 1)
            data = tar.extractfile(member).read()
            client.restore_fragment(args.index, args.frame, view, int(slice_s), data)
            n += 1
    print(f"restored {n} fragments into {args.index}/{args.frame}")
    return 0


# -- bench (ctl/bench.go:71-102) --------------------------------------------

def cmd_bench(args) -> int:
    from pilosa_tpu.server.client import Client

    client = Client(args.host)
    rng = np.random.default_rng(args.seed)
    rows = rng.integers(0, args.max_row_id, size=args.n)
    cols = rng.integers(0, args.max_column_id, size=args.n)
    if args.operation != "set-bit":
        print(f"unknown bench op: {args.operation!r}", file=sys.stderr)
        return 1
    start = time.perf_counter()
    batch = []
    for r, c in zip(rows.tolist(), cols.tolist()):
        batch.append(f'SetBit(rowID={r}, frame="{args.frame}", columnID={c})')
        if len(batch) >= args.batch_size:
            client.execute_query(args.index, " ".join(batch))
            batch = []
    if batch:
        client.execute_query(args.index, " ".join(batch))
    elapsed = time.perf_counter() - start
    print(json.dumps({"n": args.n, "seconds": round(elapsed, 3), "ops_per_sec": round(args.n / elapsed, 1)}))
    return 0


# -- check/inspect (ctl/check.go, ctl/inspect.go) ----------------------------

def cmd_check(args) -> int:
    from pilosa_tpu.roaring import Bitmap

    rc = 0
    for path in args.paths:
        try:
            with open(path, "rb") as f:
                bm = Bitmap.from_bytes(f.read())
            bm.check()
            print(f"{path}: ok ({bm.count()} bits, {len(bm.containers)} containers)")
        except Exception as e:
            print(f"{path}: FAILED: {e}", file=sys.stderr)
            rc = 1
    return rc


def cmd_inspect(args) -> int:
    from pilosa_tpu.roaring import Bitmap

    for path in args.paths:
        with open(path, "rb") as f:
            bm = Bitmap.from_bytes(f.read())
        n_array = sum(1 for c in bm.containers.values() if c.is_array)
        n_bitmap = len(bm.containers) - n_array
        print(f"{path}:")
        print(f"  bits:       {bm.count()}")
        print(f"  containers: {len(bm.containers)} ({n_array} array, {n_bitmap} bitmap)")
        print(f"  ops logged: {bm.op_n}")
        if args.verbose:
            for key in bm.sorted_keys():
                c = bm.containers[key]
                kind = "array" if c.is_array else "bitmap"
                print(f"    key={key:<8} type={kind:<6} n={c.n}")
    return 0


# -- sort (ctl/sort.go) ------------------------------------------------------

def cmd_sort(args) -> int:
    from pilosa_tpu.pilosa import SLICE_WIDTH

    rows = []
    f = sys.stdin if args.path == "-" else open(args.path)
    for line in f:
        line = line.strip()
        if not line:
            continue
        parts = line.split(",")
        rows.append((int(parts[0]), int(parts[1]), line))
    if f is not sys.stdin:
        f.close()
    rows.sort(key=lambda t: (t[1] // SLICE_WIDTH, t[0], t[1]))
    for _, _, line in rows:
        print(line)
    return 0


# -- config (ctl/config.go) --------------------------------------------------

def cmd_config(args) -> int:
    cfg = _load_config(args)
    print(cfg.to_toml(), end="")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="pilosa-tpu", description="TPU-native distributed bitmap index")
    p.add_argument("--config", help="path to TOML config file")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("server", help="run the server")
    s.add_argument("--data-dir", help="data directory")
    s.add_argument("--host", help="host:port to bind")
    s.add_argument(
        "--profile.cpu", dest="profile_cpu", metavar="PATH",
        help="write a CPU profile (pstats format) to PATH on shutdown "
             "(cmd/server.go:100 parity)",
    )
    s.add_argument("--test-exit", action="store_true", help=argparse.SUPPRESS)
    s.set_defaults(fn=cmd_server)

    s = sub.add_parser(
        "lockstep",
        help="serve queries SPMD-lockstep over a jax.distributed job (run on every rank)",
    )
    s.add_argument("--data-dir", help="holder data directory (identical data on every rank)")
    s.add_argument("--host", help="rank-0 HTTP bind host:port")
    s.add_argument("--control", default="127.0.0.1:14100", help="control-plane host:port (all ranks)")
    s.add_argument("--coordinator", help="jax.distributed coordinator host:port (omit on TPU pods)")
    s.add_argument("--num-processes", type=int, help="job size (with --coordinator)")
    s.add_argument("--process-id", type=int, help="this rank (with --coordinator)")
    s.add_argument("--local-devices", type=int, help="virtual CPU devices per process (dev rigs)")
    s.add_argument(
        "--group",
        help="replica serving-group identity for this job: name[@epoch] "
             "([replica] group / PILOSA_TPU_REPLICA_GROUP)",
    )
    s.set_defaults(fn=cmd_lockstep)

    s = sub.add_parser(
        "replica-router",
        help="route reads across replica serving groups; sequence writes to all",
    )
    s.add_argument("--host", help="router bind host:port (port part ignored; see --port)")
    s.add_argument(
        "--groups",
        help="comma-separated group front doors: host:port or name=host:port "
             "([replica] groups / PILOSA_TPU_REPLICA_GROUPS)",
    )
    s.add_argument("--port", type=int, help="router bind port ([replica] router-port)")
    s.add_argument(
        "--wal-dir", dest="wal_dir",
        help="durable write-ahead-log directory ([replica] wal-dir; "
             "omit for an in-memory log)",
    )
    s.add_argument(
        "--probe-interval", dest="probe_interval", type=float,
        help="base health-probe interval in seconds, doubled with jitter "
             "per failed probe ([replica] probe-interval)",
    )
    s.add_argument(
        "--anti-entropy-interval", dest="anti_entropy_interval", type=float,
        help="cross-group digest-compare sweep interval in seconds, "
             "jittered; 0 disables ([replica] anti-entropy-interval)",
    )
    s.add_argument(
        "--shards", type=int,
        help="partition the slice space into N shards, splitting --groups "
             "into N consecutive replica sets ([replica] shards)",
    )
    s.add_argument(
        "--shard-map", dest="shard_map",
        help="explicit shard map: 'name=lo-hi:g,g;...' with hi omitted on "
             "the open-ended tail ([replica] shard-map; wins over --shards)",
    )
    s.add_argument(
        "--shard-span", dest="shard_span", type=int,
        help="slices per shard under --shards auto-split "
             "([replica] shard-span)",
    )
    s.add_argument("--test-exit", action="store_true", help=argparse.SUPPRESS)
    s.set_defaults(fn=cmd_replica_router)

    s = sub.add_parser(
        "ingest",
        help="stream CSV row,col bits through the columnar /ingest door "
             "(resumable packed-uint64 chunks; QoS write-class backpressure)",
    )
    s.add_argument("--host", default="localhost:10101")
    s.add_argument("--index", required=True)
    s.add_argument("--frame", required=True)
    s.add_argument(
        "--chunk-pairs", type=int, default=65536,
        help="(row, col) pairs per streamed chunk (chunk bytes = 8 + 16*pairs)",
    )
    s.add_argument("paths", nargs="+")
    s.set_defaults(fn=cmd_ingest)

    s = sub.add_parser(
        "bulk",
        help="stream CSV row,col bits through the device-build /bulk door "
             "(sort/segment/scatter plane build on device, lazy roaring "
             "materialization; --arrow ships Arrow IPC chunks)",
    )
    s.add_argument("--host", default="localhost:10101")
    s.add_argument("--index", required=True)
    s.add_argument("--frame", required=True)
    s.add_argument(
        "--chunk-pairs", type=int, default=65536,
        help="(row, col) pairs per streamed chunk",
    )
    s.add_argument(
        "--arrow", action="store_true",
        help="encode chunks as Arrow IPC record batches instead of "
             "packed-uint64 framing (needs pyarrow on both ends)",
    )
    s.add_argument("paths", nargs="+")
    s.set_defaults(fn=cmd_bulk)

    s = sub.add_parser("import", help="bulk-import CSV row,col[,timestamp] bits")
    s.add_argument("--host", default="localhost:10101")
    s.add_argument("--index", required=True, dest="index")
    s.add_argument("--frame", required=True)
    s.add_argument("--buffer-size", type=int, default=10_000_000)
    s.add_argument("paths", nargs="+")
    s.set_defaults(fn=cmd_import)

    s = sub.add_parser("export", help="export a frame as CSV or Arrow")
    s.add_argument("--host", default="localhost:10101")
    s.add_argument("--index", required=True)
    s.add_argument("--frame", required=True)
    s.add_argument("--view", default="standard")
    s.add_argument(
        "--format", choices=("csv", "arrow"), default="csv",
        help="csv row,col lines or Arrow IPC record batches "
             "(one stream per slice, concatenated)",
    )
    s.add_argument("-o", "--output", default="-")
    s.set_defaults(fn=cmd_export)

    s = sub.add_parser("backup", help="backup a frame to a tar archive")
    s.add_argument("--host", default="localhost:10101")
    s.add_argument("--index", required=True)
    s.add_argument("--frame", required=True)
    s.add_argument("-o", "--output", required=True)
    s.set_defaults(fn=cmd_backup)

    s = sub.add_parser("restore", help="restore a frame from a tar archive")
    s.add_argument("--host", default="localhost:10101")
    s.add_argument("--index", required=True)
    s.add_argument("--frame", required=True)
    s.add_argument("-i", "--input", required=True)
    s.set_defaults(fn=cmd_restore)

    s = sub.add_parser("bench", help="run a benchmark against a server")
    s.add_argument("--host", default="localhost:10101")
    s.add_argument("--index", required=True)
    s.add_argument("--frame", required=True)
    s.add_argument("-o", "--operation", default="set-bit")
    s.add_argument("-n", type=int, default=1000, dest="n")
    s.add_argument("--max-row-id", type=int, default=1000)
    s.add_argument("--max-column-id", type=int, default=1000)
    s.add_argument("--batch-size", type=int, default=100)
    s.add_argument("--seed", type=int, default=0)
    s.set_defaults(fn=cmd_bench)

    s = sub.add_parser("check", help="verify fragment file consistency")
    s.add_argument("paths", nargs="+")
    s.set_defaults(fn=cmd_check)

    s = sub.add_parser("inspect", help="dump fragment container stats")
    s.add_argument("-v", "--verbose", action="store_true")
    s.add_argument("paths", nargs="+")
    s.set_defaults(fn=cmd_inspect)

    s = sub.add_parser("sort", help="pre-sort an import CSV by slice position")
    s.add_argument("path")
    s.set_defaults(fn=cmd_sort)

    s = sub.add_parser("config", help="print the effective configuration")
    s.add_argument("--data-dir", help="data directory")
    s.add_argument("--host", help="host:port")
    s.set_defaults(fn=cmd_config)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except KeyboardInterrupt:
        return 130
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
