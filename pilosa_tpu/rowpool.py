"""Paged device-resident row pool: the HBM working set without a row cap.

Round-1's fused query lanes kept ONE device matrix per (frame, view,
slice-batch) holding exactly the rows ever referenced, hard-capped at
``PILOSA_TPU_MATRIX_ROWS_MAX`` rows — past the cap every request fell back
to host numpy.  The reference has no such ceiling: its rank cache tracks
``DefaultCacheSize=50000`` rows per fragment (frame.go:33-40,
cache.go:126-275) and rows page between mmap and memory on demand
(fragment.go:338-367).

This module is the TPU-native replacement: a fixed-capacity slot pool
``uint32[n_slices, capacity, W]`` in device memory.  Rows page in on
demand (host roaring -> dense -> one scatter per miss batch), LRU rows
page out when the pool is full, and the capacity itself grows by
power-of-two doubling up to an HBM budget.  Query kernels index rows by
SLOT id — the same gather kernels as before, they never cared whether
slot assignment was dense or paged.

Consistency model: every content change produces a NEW engine array
(functional ``.at[].set``), so a reader that acquired ``(positions,
matrix)`` holds an immutable snapshot — a concurrent eviction can only
affect later acquires, never a result in flight.  Write invalidation is
generation-based exactly like the old cache: stale slices get their
planes re-fetched (bounded), or the pool resets when a refresh would
cost more than repopulating on demand.
"""

from __future__ import annotations

import os
import threading

from pilosa_tpu.analysis import lockcheck
from collections import OrderedDict
from typing import Callable, Optional, Sequence

import numpy as np

def _pool_bytes() -> int:
    """HBM budget for ONE pool's matrix (read per call: benches and tests
    tune it).  Total pool memory is bounded by this times the executor's
    matrix-cache entry count; transient peaks reach 2x one pool during a
    functional scatter (old + new array alive)."""
    # analysis-ok: lockstep-determinism: deployment config, launcher sets identical env on every rank
    return int(os.environ.get("PILOSA_TPU_POOL_BYTES", str(2 * 1024 * 1024 * 1024)))


def _refresh_bytes_max() -> int:
    """A stale-slice plane refresh re-uploads every resident row for those
    slices; past this many bytes a reset-and-repopulate is cheaper than
    the blind refresh (writes invalidated most of what residency was
    worth)."""
    return int(os.environ.get("PILOSA_TPU_POOL_REFRESH_BYTES", str(512 * 1024 * 1024)))


def pool_capacity(n_slices: int, words: int, budget_bytes: int = 0) -> int:
    """Slot capacity the budget allows for an ``[n_slices, cap, W]`` pool."""
    budget = budget_bytes or _pool_bytes()
    return max(0, budget // max(1, n_slices * words * 4))


def _pow2(n: int) -> int:
    return 1 << (n - 1).bit_length() if n > 1 else 1


class DeviceRowPool:
    """One frame-view's paged row working set over a fixed slice batch.

    ``fetch(row_ids, slice_idxs) -> uint32[len(slice_idxs), len(row_ids), W]``
    pulls dense rows from host storage (fragment ``row_dense``).
    """

    def __init__(
        self,
        engine,
        n_slices: int,
        words: int,
        fetch: Callable[[Sequence[int], Sequence[int]], np.ndarray],
        cap_max: int = 0,
        row_major: bool = False,
    ):
        self.engine = engine
        self.n_slices = n_slices
        self.words = words
        self.fetch = fetch
        # Row-major pools store [cap, n_slices, W] (tiled) so the gather
        # regime's kernels get one contiguous DMA descriptor per operand
        # row; ``fetch`` must then return [len(row_ids), len(slice_idxs),
        # W] blocks (the executor's densify fills either order directly).
        # Slice-major (default) matches mesh sharding and the Gram/TopN
        # lanes.
        self.row_major = row_major
        # 0 = budget-driven (re-read per access so a retuned
        # PILOSA_TPU_POOL_BYTES applies to cached pools, keeping this in
        # lockstep with callers that consult pool_capacity() directly).
        self._cap_override = cap_max
        self.mu = lockcheck.named_rlock("rowpool.mu")
        self.gens: Optional[tuple] = None
        self.matrix = None  # engine array [n_slices, cap, W]
        self.cap = 0
        self.slot_of: dict[int, int] = {}
        self.row_at: list[Optional[int]] = []
        self.lru: OrderedDict[int, None] = OrderedDict()
        self.box: dict = self._new_box()
        # Telemetry for benches/tests: paging behavior must be observable.
        self.stat_misses = 0
        self.stat_evictions = 0
        self.stat_resets = 0
        self.stat_repairs = 0
        # (row, slice) planes actually fetched by the patch lane — the
        # per-(row, slice) granularity benches/tests assert on this.
        self.stat_patch_planes = 0

    @staticmethod
    def default_cap(n_slices: int, words: int) -> int:
        """The budget-driven cap an un-overridden pool would report —
        shared with callers that must predict a pool's capacity WITHOUT
        instantiating it (executor lane probes)."""
        return max(1, pool_capacity(n_slices, words))

    @property
    def cap_max(self) -> int:
        if self._cap_override:
            return self._cap_override
        return self.default_cap(self.n_slices, self.words)

    @cap_max.setter
    def cap_max(self, v: int) -> None:
        self._cap_override = v

    def _new_box(self) -> dict:
        # Same contract as the old matrix-cache "box": holds the Gram and
        # its lut, dies on ANY content change.  id_pos is the full
        # row->slot snapshot (immutable; rebuilt per box) so steady-state
        # hits hand out positions without copying; n_used bounds the slot
        # range in use so Gram builds can ignore free capacity tail.
        return {
            "hits": 0,
            "mu": lockcheck.named_lock("rowpool.entry_mu"),
            "id_pos": dict(self.slot_of),
            "n_used": max(self.slot_of.values(), default=-1) + 1,
        }

    # -- internals (call with self.mu held) ------------------------------

    def _grow_to(self, need: int) -> None:
        new_cap = min(self.cap_max, _pow2(need))
        if new_cap <= self.cap:
            return
        if self.matrix is None or self.cap == 0:
            if self.row_major:
                host = np.zeros((new_cap, self.n_slices, self.words), dtype=np.uint32)
                self.matrix = self.engine.matrix_rows(host)
            else:
                host = np.zeros((self.n_slices, new_cap, self.words), dtype=np.uint32)
                self.matrix = self.engine.matrix(host)
        elif self.row_major:
            self.matrix = self.engine.grow_rows_rm(self.matrix, new_cap - self.cap)
        else:
            # Zero capacity appended device-side (no host transfer).
            self.matrix = self.engine.grow_rows(self.matrix, new_cap - self.cap)
        self.row_at.extend([None] * (new_cap - self.cap))
        self.cap = new_cap

    def _reset(self) -> None:
        self.slot_of.clear()
        self.lru.clear()
        self.row_at = [None] * self.cap
        # Matrix contents are stale garbage but unreferenced: no slot maps
        # to them, and gathers only index mapped slots.
        self.stat_resets += 1

    def _refresh_stale(self, stale: list[int]) -> None:
        """Re-pull resident rows' planes for written slices, or reset.

        Only the RESIDENT slots are scattered (set_plane_rows) — a
        whole-plane replacement would transfer the full capacity width,
        mostly zeros, undercutting the byte budget this check enforces.
        """
        if not self.slot_of:
            return
        if len(self.slot_of) * len(stale) * self.words * 4 > _refresh_bytes_max():
            self._reset()
            return
        rows = sorted(self.slot_of, key=self.slot_of.get)
        slots = [self.slot_of[r] for r in rows]
        block = self.fetch(rows, stale)  # layout per self.row_major
        if self.row_major:  # block: [len(rows), len(stale), W]
            self.matrix = self.engine.set_plane_rows_rm(
                self.matrix, stale, slots, block
            )
        else:  # block: [len(stale), len(rows), W]
            self.matrix = self.engine.set_plane_rows(self.matrix, stale, slots, block)

    def _repair_dirty(self, stale: list[int], dirty_rows) -> bool:
        """Patch ONLY the written (row, slice) planes and rank-k-repair
        the box Gram, instead of the blind whole-plane refresh + box
        reset: the box (and with it the Gram, its glut, and the id_pos
        snapshot) SURVIVES the write, so a small write costs O(dirty
        planes) row fetches plus one dirty x resident pair-count
        dispatch — not an O(R^2) Gram rebuild.  ``dirty_rows`` is either
        a ``{slice_index: rows}`` mapping (per-(row, slice) granularity:
        each stale slice re-fetches only the rows written IN that slice)
        or a flat row iterable (legacy: every dirty row re-fetched
        across every stale slice).  The caller (executor) guarantees it
        covers every row whose storage changed across the stale slices
        (fragment dirty-row journals); rows not resident in the pool
        need no patch at all.  Returns False (nothing mutated) when the
        dirty slots fall outside the Gram's slot range — an invariant
        breach that the conservative full refresh handles."""
        if isinstance(dirty_rows, dict):
            per_slice = {
                si: sorted(r for r in set(dirty_rows.get(si, ())) if r in self.slot_of)
                for si in stale
            }
        else:
            flat = sorted(r for r in set(dirty_rows) if r in self.slot_of)
            per_slice = {si: flat for si in stale}
        patched = [si for si in stale if per_slice[si]]
        if not patched:
            return True  # writes only touched rows the pool doesn't hold
        all_slots = sorted({self.slot_of[r] for si in patched for r in per_slice[si]})
        gram = self.box.get("gram")
        if gram is not None and any(s >= gram.shape[0] for s in all_slots):
            return False  # defensive: slot outside the Gram bucket
        old_matrix = self.matrix  # pre-patch snapshot (functional updates)
        # One fetch + one scatter per distinct row set: slices written
        # with the same rows batch into a single transfer, and a slice
        # whose dirty rows aren't resident costs nothing at all.
        by_rows: dict[tuple, list[int]] = {}
        for si in patched:
            by_rows.setdefault(tuple(per_slice[si]), []).append(si)
        for rows_t, group in by_rows.items():
            rows = list(rows_t)
            slots = [self.slot_of[r] for r in rows]
            block = self.fetch(rows, group)  # layout per self.row_major
            self.stat_patch_planes += len(rows) * len(group)
            if self.row_major:
                self.matrix = self.engine.set_plane_rows_rm(
                    self.matrix, group, slots, block
                )
            else:
                self.matrix = self.engine.set_plane_rows(
                    self.matrix, group, slots, block
                )
        if gram is not None:
            d = gram.shape[0]
            m = self.matrix if d == self.cap else self.matrix[:, :d]
            m_old = old_matrix if d == self.cap else old_matrix[:, :d]
            gram = self.engine.gram_update_rows(
                m, gram, all_slots, old_matrix=m_old, slice_idxs=patched
            )
            self.box["gram"] = gram
            glut = self.box.get("gram_lut")
            if glut is not None:
                # rs/ps are membership-keyed and membership didn't change;
                # only the count table is new.
                self.box["gram_lut"] = (glut[0], np.ascontiguousarray(gram), glut[2])
        return True

    # -- API --------------------------------------------------------------

    def acquire(self, want: Sequence[int], gens: tuple, dirty_rows=None):
        """Ensure ``want`` rows are resident; returns (id_pos, matrix, box).

        ``id_pos`` maps every RESIDENT row id to its slot (a stable
        snapshot — safe to index concurrently); ``matrix`` is the engine
        array snapshot those slots refer to.  Raises ValueError when
        ``want`` alone exceeds the pool capacity — callers chunk their
        query batch by unique-row count first (``chunk_queries``).

        ``dirty_rows``: the complete delta written since this pool's
        recorded generations (from the fragment dirty-row journals) —
        either a ``{slice_index: rows}`` mapping (per-(row, slice)
        granularity) or a flat row set (every row dirty in every stale
        slice) — or None when unknown.  When provided, a generation
        mismatch takes the PATCH lane (_repair_dirty) and the cache box
        — including a warm Gram — survives the write.
        """
        want = list(dict.fromkeys(want))  # de-dup, keep order
        if len(want) > self.cap_max:
            raise ValueError(
                f"want {len(want)} rows > pool capacity {self.cap_max}; chunk the batch"
            )
        with self.mu:
            changed = False
            if self.gens != gens:
                if self.gens is not None:
                    stale = [
                        si for si in range(self.n_slices) if self.gens[si] != gens[si]
                    ]
                    if stale:
                        if dirty_rows is not None and self._repair_dirty(
                            stale, dirty_rows
                        ):
                            self.stat_repairs += 1
                        else:
                            self._refresh_stale(stale)
                            changed = True
                self.gens = gens
            missing = [r for r in want if r not in self.slot_of]
            if missing:
                self.stat_misses += len(missing)
                changed = True
                need = len(self.slot_of) + len(missing)
                if need > self.cap:
                    self._grow_to(need)
                free = [s for s in range(self.cap) if self.row_at[s] is None]
                if len(free) < len(missing):
                    want_set = set(want)
                    for victim in list(self.lru):
                        if len(free) >= len(missing):
                            break
                        if victim in want_set:
                            continue
                        s = self.slot_of.pop(victim)
                        del self.lru[victim]
                        self.row_at[s] = None
                        free.append(s)
                        self.stat_evictions += 1
                slots = free[: len(missing)]
                block = self.fetch(missing, list(range(self.n_slices)))
                if self.row_major:  # block: [len(missing), S, W]
                    self.matrix = self.engine.set_rows_at_rm(
                        self.matrix, slots, block
                    )
                else:
                    self.matrix = self.engine.set_rows_at(self.matrix, slots, block)
                for r, s in zip(missing, slots):
                    self.slot_of[r] = s
                    self.row_at[s] = r
            for r in want:
                self.lru[r] = None
                self.lru.move_to_end(r)
            if changed:
                self.box = self._new_box()
            # The generations THIS box's matrix content was validated
            # against: consumers deriving cached state from the box (the
            # executor's serve-state capture) must use these as validity
            # tokens, not generations re-read later — a write landing
            # between acquire and capture would otherwise stamp post-
            # write tokens onto pre-write data (permanent stale serves).
            self.box["gens"] = gens
            self.box["hits"] += 1
            return self.box["id_pos"], self.matrix, self.box


def chunk_queries(
    queries: Sequence, rows_of: Callable, cap: int, oversize_ok: bool = False
) -> list[list]:
    """Partition a query batch so each chunk's UNIQUE row set fits ``cap``.

    Greedy in arrival order (preserves per-chunk dispatch order).  A
    single query whose own rows exceed cap has no valid chunking: with
    ``oversize_ok`` it becomes its own chunk (the caller's slice-streaming
    branch handles any row count); otherwise it raises.
    """
    chunks: list[list] = []
    cur: list = []
    cur_rows: set = set()
    for q in queries:
        rows = set(rows_of(q))
        if len(rows) > cap:
            if not oversize_ok:
                raise ValueError(
                    f"single query references {len(rows)} rows > capacity {cap}"
                )
            if cur:
                chunks.append(cur)
                cur, cur_rows = [], set()
            chunks.append([q])
            continue
        if cur and len(cur_rows | rows) > cap:
            chunks.append(cur)
            cur, cur_rows = [], set()
        cur.append(q)
        cur_rows |= rows
    if cur:
        chunks.append(cur)
    return chunks
