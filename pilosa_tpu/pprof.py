"""pprof-format profile encoder (google/pprof profile.proto, proto3).

The reference mounts net/http/pprof (handler.go:99), whose default
output is a gzipped protobuf Profile consumable by ``go tool pprof`` /
``pprof -http``.  This module hand-rolls that encoding with the same
varint/length-delimited writer the HTTP data plane uses (pilosa_tpu.wire
— no protobuf library dependency), so this build's ``/debug/pprof/``
endpoints serve REAL pprof payloads, not just text dumps.

profile.proto field numbers (public pprof schema):
  Profile:   1 sample_type  2 sample  4 location  5 function
             6 string_table  9 time_nanos  10 duration_nanos
             12 period_type  13 period
  ValueType: 1 type(str idx)  2 unit(str idx)
  Sample:    1 location_id (packed)  2 value (packed)
  Location:  1 id  4 line
  Line:      1 function_id  2 line
  Function:  1 id  2 name  3 system_name  4 filename  5 start_line
"""

from __future__ import annotations

import gzip
import sys
import threading
import time
import traceback
from collections import Counter

from pilosa_tpu.wire import Writer


class _Strings:
    """String table: index 0 is always ""."""

    def __init__(self):
        self.table: list[str] = [""]
        self.index: dict[str, int] = {"": 0}

    def __call__(self, s: str) -> int:
        i = self.index.get(s)
        if i is None:
            i = self.index[s] = len(self.table)
            self.table.append(s)
        return i


def _value_type(st: _Strings, typ: str, unit: str) -> bytes:
    return Writer().varint(1, st(typ)).varint(2, st(unit)).finish()


def build_profile(
    samples: list[tuple[list[tuple[str, str, int]], list[int]]],
    sample_types: list[tuple[str, str]],
    period_type: tuple[str, str] | None = None,
    period: int = 0,
    duration_nanos: int = 0,
) -> bytes:
    """Gzipped pprof Profile.

    ``samples``: (stack, values) pairs; stack = [(function_name,
    filename, line), ...] ordered leaf-first (pprof convention);
    ``values`` aligned with ``sample_types`` [(type, unit), ...].
    """
    st = _Strings()
    w = Writer()
    for typ, unit in sample_types:
        w.message(1, _value_type(st, typ, unit))

    # Dedupe locations/functions across samples.
    fn_ids: dict[tuple[str, str], int] = {}
    loc_ids: dict[tuple[str, str, int], int] = {}
    fn_msgs: list[bytes] = []
    loc_msgs: list[bytes] = []

    def loc_id(frame: tuple[str, str, int]) -> int:
        lid = loc_ids.get(frame)
        if lid is not None:
            return lid
        name, filename, line = frame
        fkey = (name, filename)
        fid = fn_ids.get(fkey)
        if fid is None:
            fid = fn_ids[fkey] = len(fn_msgs) + 1
            fn_msgs.append(
                Writer()
                .varint(1, fid)
                .varint(2, st(name))
                .varint(3, st(name))
                .varint(4, st(filename))
                .finish()
            )
        lid = loc_ids[frame] = len(loc_msgs) + 1
        line_msg = Writer().varint(1, fid).varint(2, line).finish()
        loc_msgs.append(Writer().varint(1, lid).message(4, line_msg).finish())
        return lid

    sample_msgs = []
    for stack, values in samples:
        ids = [loc_id(f) for f in stack]
        sample_msgs.append(Writer().packed(1, ids).packed(2, values).finish())

    for m in sample_msgs:
        w.message(2, m)
    for m in loc_msgs:
        w.message(4, m)
    for m in fn_msgs:
        w.message(5, m)
    for s in st.table:
        w.bytes_field(6, s.encode("utf-8"), force=True)
    w.varint(9, time.time_ns())
    if duration_nanos:
        w.varint(10, duration_nanos)
    if period_type is not None:
        w.message(12, _value_type(st, *period_type))
    if period:
        w.varint(13, period)
    return gzip.compress(w.finish())


def _frame_stack(frame) -> list[tuple[str, str, int]]:
    """Leaf-first (function, file, line) stack for a Python frame."""
    out = []
    f = frame
    while f is not None:
        # co_qualname is 3.11+; co_name keeps 3.10 serving (just less
        # qualified frame names in the profile).
        name = getattr(f.f_code, "co_qualname", f.f_code.co_name)
        out.append((name, f.f_code.co_filename, f.f_lineno))
        f = f.f_back
    return out


def thread_profile() -> bytes:
    """One sample per live thread — the ``goroutine`` profile analog."""
    names = {t.ident: t.name for t in threading.enumerate()}
    samples = []
    for tid, frame in sys._current_frames().items():
        stack = _frame_stack(frame)
        # Thread identity as the root pseudo-frame, like goroutine ids.
        stack.append((f"thread {names.get(tid, tid)}", "", 0))
        samples.append((stack, [1]))
    return build_profile(samples, [("threads", "count")])


def cpu_profile(seconds: float, hz: int = 100) -> bytes:
    """Sampling CPU profile: every thread's Python stack at ``hz`` for
    ``seconds`` (the /debug/pprof/profile analog; sampling, like pprof's,
    not tracing — negligible overhead on the serving path)."""
    interval = 1.0 / hz
    period_ns = int(1e9 / hz)
    counts: Counter[tuple] = Counter()
    deadline = time.monotonic() + seconds
    me = threading.get_ident()
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # the sampler itself is not workload
            counts[tuple(_frame_stack(frame))] += 1
        time.sleep(interval)
    samples = [
        (list(stack), [n, n * period_ns]) for stack, n in counts.items()
    ]
    return build_profile(
        samples,
        [("samples", "count"), ("cpu", "nanoseconds")],
        period_type=("cpu", "nanoseconds"),
        period=period_ns,
        duration_nanos=int(seconds * 1e9),
    )


def text_threads() -> str:
    """Human-readable thread dump (the ?debug=1 form)."""
    import io

    out = io.StringIO()
    for tid, frame in sys._current_frames().items():
        out.write(f"--- thread {tid} ---\n")
        out.write("".join(traceback.format_stack(frame)))
    return out.getvalue()
