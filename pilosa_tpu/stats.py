"""Stats clients: counters/gauges/histograms with tag support.

Reference analog: stats.go — the StatsClient interface
(Count/Gauge/Histogram/Set/Timing/WithTags, stats.go:33-54), the
expvar-backed client (stats.go:70-130), MultiStatsClient (stats.go:133-185)
and the datadog statsd sink (datadog/datadog.go).  Here the statsd sink
speaks the plain UDP statsd wire format (datadog-compatible with |#tags).
"""

from __future__ import annotations

import random
import socket
import threading

from pilosa_tpu.analysis import lockcheck
from collections import defaultdict
from typing import Iterable

# Per-series sample cap for the expvar histogram/timing reservoirs: a
# long-lived server records totals/min/max exactly and keeps a uniform
# Algorithm-R sample of this size for the percentiles, instead of
# appending every observation forever.
RESERVOIR_CAP = 4096

# A write shard self-flushes into the base maps once it holds this many
# pending histogram/timing samples, bounding per-thread memory between
# snapshots.
SHARD_FLUSH_CAP = 512


@lockcheck.guarded_class
class _StatsShard:
    """One thread's private write buffer inside ExpvarStatsClient.

    Writers touch only their own shard under its (uncontended) shard
    lock; the base maps are only reached by a drain, which holds the
    client lock THEN the shard lock.  The drain moves-and-zeroes the
    shard state in one shard-lock hold, so a given delta is merged into
    the base maps exactly once — a shard self-flushing mid-snapshot
    serializes on the client lock and cannot be double-counted.
    """

    _guarded_by_ = {
        "counters": "stats._shard",
        "hist_meta": "stats._shard",
        "hist_pending": "stats._shard",
        "timing_meta": "stats._shard",
        "timing_pending": "stats._shard",
        "pending_n": "stats._shard",
    }

    __slots__ = (
        "lock", "counters", "hist_meta", "hist_pending",
        "timing_meta", "timing_pending", "pending_n",
    )

    def __init__(self):
        self.lock = lockcheck.named_lock("stats._shard")
        with self.lock:
            self.counters: dict[str, int] = {}
            # Exact per-series deltas since the last drain: [count, min,
            # max, sum] for histograms, [count, sum] for timings, plus
            # every pending sample (fed through the base reservoir at
            # drain so sampling odds match the serialized client).
            self.hist_meta: dict[str, list[float]] = {}
            self.hist_pending: dict[str, list[float]] = {}
            self.timing_meta: dict[str, list[float]] = {}
            self.timing_pending: dict[str, list[float]] = {}
            self.pending_n = 0


class NopStatsClient:
    def with_tags(self, *tags: str) -> "NopStatsClient":
        return self

    def count(self, name: str, value: int = 1) -> None:
        pass

    def gauge(self, name: str, value: float) -> None:
        pass

    def histogram(self, name: str, value: float) -> None:
        pass

    def set(self, name: str, value: str) -> None:
        pass

    def timing(self, name: str, value: float) -> None:
        pass


# Shared null-object instance: data-model objects coerce stats=None to
# this so emission sites need no truthiness guards.
NOP_STATS = NopStatsClient()


class ExpvarStatsClient:
    """In-process stats exposed at /debug/vars (stats.go:70-130).

    Counter/histogram/timing writes land in per-thread shards
    (_StatsShard) so N serving threads don't serialize on one client
    lock; snapshot()/snapshot_typed() drain every shard under the
    client lock and render from the merged base maps in the same hold —
    one consistent snapshot, totals exactly equal to the serialized
    client's.  Gauges and sets are last-writer-wins and stay under the
    client lock (cross-shard write ordering would be meaningless).
    """

    def __init__(self, tags: tuple[str, ...] = ()):
        self._lock = lockcheck.named_lock("stats._lock")
        self._counters: dict[str, int] = defaultdict(int)
        self._gauges: dict[str, float] = {}
        self._sets: dict[str, str] = {}
        # Bounded reservoirs (RESERVOIR_CAP samples) + exact running
        # metadata per series: [count, min, max, sum] for histograms,
        # [count, sum] for timings.
        self._histograms: dict[str, list[float]] = defaultdict(list)
        self._hist_meta: dict[str, list[float]] = {}
        self._timings: dict[str, list[float]] = defaultdict(list)
        self._timing_meta: dict[str, list[float]] = {}
        self._rng = random.Random(0)
        self._tags = tags
        self._children: dict[tuple[str, ...], ExpvarStatsClient] = {}
        # Per-thread write shards; the registry list is guarded by
        # _lock, each shard's contents by its own lock.  Tagged children
        # share both (keys embed the tags before they reach a shard).
        self._shards: list[_StatsShard] = []
        self._shard_local = threading.local()

    def _key(self, name: str) -> str:
        return f"{name}[{','.join(self._tags)}]" if self._tags else name

    def with_tags(self, *tags: str) -> "ExpvarStatsClient":
        key = tuple(sorted(set(self._tags) | set(tags)))
        # Locked lookup-or-create: every handler thread reaches here
        # (tenant/class tags), and the unlocked get-then-store lost a
        # child — or tears _children outright without the GIL.
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = ExpvarStatsClient(tags=key)
                # share the top-level maps so /debug/vars sees everything
                child._lock = self._lock
                child._counters = self._counters
                child._gauges = self._gauges
                child._sets = self._sets
                child._histograms = self._histograms
                child._hist_meta = self._hist_meta
                child._timings = self._timings
                child._timing_meta = self._timing_meta
                child._rng = self._rng
                child._shards = self._shards
                child._shard_local = self._shard_local
                self._children[key] = child
            return child

    def _shard(self) -> _StatsShard:
        sh = getattr(self._shard_local, "shard", None)
        if sh is None:
            sh = _StatsShard()
            with self._lock:
                self._shards.append(sh)
            self._shard_local.shard = sh
        return sh

    def shard_count(self) -> int:
        """Live write shards (== threads that have emitted); exported
        as the ``stats.shards`` gauge by the metrics endpoints."""
        with self._lock:
            return len(self._shards)

    def count(self, name: str, value: int = 1) -> None:
        sh = self._shard()
        with sh.lock:
            key = self._key(name)
            sh.counters[key] = sh.counters.get(key, 0) + value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[self._key(name)] = value

    def _reservoir_add(self, samples: list[float], n_total: int, value: float) -> None:
        """Algorithm R: every observation has cap/n odds of residing in
        the sample once the reservoir is full — bounded memory, uniform
        percentiles."""
        if len(samples) < RESERVOIR_CAP:
            samples.append(value)
            return
        j = self._rng.randrange(n_total)
        if j < RESERVOIR_CAP:
            samples[j] = value

    def histogram(self, name: str, value: float) -> None:
        sh = self._shard()
        with sh.lock:
            key = self._key(name)
            meta = sh.hist_meta.get(key)
            if meta is None:
                meta = sh.hist_meta[key] = [0, value, value, 0.0]
            meta[0] += 1
            meta[1] = min(meta[1], value)
            meta[2] = max(meta[2], value)
            meta[3] += value
            sh.hist_pending.setdefault(key, []).append(value)
            sh.pending_n += 1
            flush = sh.pending_n >= SHARD_FLUSH_CAP
        if flush:
            self._flush_shard(sh)

    def set(self, name: str, value: str) -> None:
        with self._lock:
            self._sets[self._key(name)] = value

    def timing(self, name: str, value: float) -> None:
        sh = self._shard()
        with sh.lock:
            key = self._key(name)
            meta = sh.timing_meta.get(key)
            if meta is None:
                meta = sh.timing_meta[key] = [0, 0.0]
            meta[0] += 1
            meta[1] += value
            sh.timing_pending.setdefault(key, []).append(value)
            sh.pending_n += 1
            flush = sh.pending_n >= SHARD_FLUSH_CAP
        if flush:
            self._flush_shard(sh)

    def _flush_shard(self, sh: _StatsShard) -> None:
        """Writer-side self-flush (pending cap reached).  Same client →
        shard lock order as the snapshot drain, so a flush racing a
        snapshot merges the shard's deltas exactly once."""
        with self._lock:
            self._drain_shard_locked(sh)

    def _drain_shard_locked(self, sh: _StatsShard) -> None:
        """Merge one shard into the base maps.  Caller holds _lock; the
        shard state is moved-and-zeroed in a single shard-lock hold so
        no delta can be observed (or merged) twice."""
        with sh.lock:
            if not sh.counters and not sh.hist_meta and not sh.timing_meta:
                return
            counters = sh.counters
            sh.counters = {}
            hist_meta = sh.hist_meta
            sh.hist_meta = {}
            hist_pending = sh.hist_pending
            sh.hist_pending = {}
            timing_meta = sh.timing_meta
            sh.timing_meta = {}
            timing_pending = sh.timing_pending
            sh.timing_pending = {}
            sh.pending_n = 0
        for key, v in counters.items():
            self._counters[key] += v
        for key, d in hist_meta.items():
            meta = self._hist_meta.get(key)
            if meta is None:
                self._hist_meta[key] = list(d)
            else:
                meta[0] += d[0]
                meta[1] = min(meta[1], d[1])
                meta[2] = max(meta[2], d[2])
                meta[3] += d[3]
        for key, vals in hist_pending.items():
            # Replay through the reservoir at the merged running count
            # (every observation since the last drain is pending, so
            # base + i + 1 is the true stream position).
            samples = self._histograms[key]
            base = int(self._hist_meta[key][0]) - len(vals)
            for i, v in enumerate(vals):
                self._reservoir_add(samples, base + i + 1, v)
        for key, d in timing_meta.items():
            meta = self._timing_meta.get(key)
            if meta is None:
                self._timing_meta[key] = list(d)
            else:
                meta[0] += d[0]
                meta[1] += d[1]
        for key, vals in timing_pending.items():
            samples = self._timings[key]
            base = int(self._timing_meta[key][0]) - len(vals)
            for i, v in enumerate(vals):
                self._reservoir_add(samples, base + i + 1, v)

    def _drain_all_locked(self) -> None:
        for sh in self._shards:
            self._drain_shard_locked(sh)

    def snapshot(self) -> dict:
        with self._lock:
            self._drain_all_locked()
            out: dict = dict(self._counters)
            out.update(self._gauges)
            out.update(self._sets)
            for name, vals in self._histograms.items():
                if vals:
                    # count/min/max are exact totals; the percentiles
                    # (p50/p95/p99 — the dashboard set, so consumers of
                    # e.g. qos.latency_ms.<class> never re-derive them
                    # from raw samples) read the bounded reservoir.
                    n_total, lo, hi = self._hist_meta[name][:3]
                    s = sorted(vals)
                    out[name] = {
                        "count": int(n_total),
                        "min": lo,
                        "max": hi,
                        "p50": s[len(s) // 2],
                        "p95": s[min(len(s) - 1, int(len(s) * 0.95))],
                        "p99": s[min(len(s) - 1, int(len(s) * 0.99))],
                    }
            for name, vals in self._timings.items():
                if vals:
                    n_total, total = self._timing_meta[name]
                    out[name + ".avg_ms"] = total / n_total * 1000
            return out

    def snapshot_typed(self) -> dict:
        """Kind-preserving snapshot for the Prometheus exposition
        (metrics.py): /debug/vars' flat snapshot() merges counters,
        gauges and sets into one dict, which cannot be mapped back to
        Prometheus metric types mechanically — this keeps each family
        separate.  Histogram entries carry the exact running
        count/min/max/sum plus reservoir percentiles; timings carry
        count/sum.  Shards are drained first, under the same single
        lock hold the render reads from — one consistent snapshot."""
        with self._lock:
            self._drain_all_locked()
            hists: dict = {}
            for name, vals in self._histograms.items():
                if vals:
                    n_total, lo, hi, total = self._hist_meta[name]
                    s = sorted(vals)
                    hists[name] = {
                        "count": int(n_total),
                        "min": lo,
                        "max": hi,
                        "sum": total,
                        "p50": s[len(s) // 2],
                        "p95": s[min(len(s) - 1, int(len(s) * 0.95))],
                        "p99": s[min(len(s) - 1, int(len(s) * 0.99))],
                    }
            timings = {
                name: {"count": int(meta[0]), "sum": meta[1]}
                for name, meta in self._timing_meta.items()
                if meta[0]
            }
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "sets": dict(self._sets),
                "histograms": hists,
                "timings": timings,
            }


class StatsdStatsClient:
    """UDP statsd sink with datadog-style |#tag lists (datadog/datadog.go)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125, prefix: str = "pilosa.", tags: tuple[str, ...] = ()):
        self.addr = (host, port)
        self.prefix = prefix
        self._tags = tags
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def with_tags(self, *tags: str) -> "StatsdStatsClient":
        c = StatsdStatsClient.__new__(StatsdStatsClient)
        c.addr = self.addr
        c.prefix = self.prefix
        c._tags = tuple(sorted(set(self._tags) | set(tags)))
        c._sock = self._sock
        return c

    def _send(self, payload: str) -> None:
        if self._tags:
            payload += "|#" + ",".join(self._tags)
        try:
            self._sock.sendto(payload.encode(), self.addr)
        except OSError:
            pass

    def count(self, name: str, value: int = 1) -> None:
        self._send(f"{self.prefix}{name}:{value}|c")

    def gauge(self, name: str, value: float) -> None:
        self._send(f"{self.prefix}{name}:{value}|g")

    def histogram(self, name: str, value: float) -> None:
        self._send(f"{self.prefix}{name}:{value}|h")

    def set(self, name: str, value: str) -> None:
        self._send(f"{self.prefix}{name}:{value}|s")

    def timing(self, name: str, value: float) -> None:
        self._send(f"{self.prefix}{name}:{value * 1000:.3f}|ms")


class MultiStatsClient:
    """Fan out to several clients (stats.go:133-185)."""

    def __init__(self, clients: Iterable):
        self.clients = list(clients)

    def with_tags(self, *tags: str) -> "MultiStatsClient":
        return MultiStatsClient([c.with_tags(*tags) for c in self.clients])

    def count(self, name: str, value: int = 1) -> None:
        for c in self.clients:
            c.count(name, value)

    def gauge(self, name: str, value: float) -> None:
        for c in self.clients:
            c.gauge(name, value)

    def histogram(self, name: str, value: float) -> None:
        for c in self.clients:
            c.histogram(name, value)

    def set(self, name: str, value: str) -> None:
        for c in self.clients:
            c.set(name, value)

    def timing(self, name: str, value: float) -> None:
        for c in self.clients:
            c.timing(name, value)

    def snapshot(self) -> dict:
        for c in self.clients:
            if hasattr(c, "snapshot"):
                return c.snapshot()
        return {}

    def snapshot_typed(self) -> dict:
        for c in self.clients:
            if hasattr(c, "snapshot_typed"):
                return c.snapshot_typed()
        return {}


def new_stats_client(spec: str):
    """Build a stats client from a config string: "expvar" (default),
    "statsd[:host[:port]]", or "nop" (cmd/server.go stats wiring analog)."""
    spec = (spec or "expvar").strip()
    if spec in ("nop", "none", ""):
        return NopStatsClient()
    if spec == "expvar":
        return ExpvarStatsClient()
    if spec == "statsd" or spec.startswith("statsd:"):
        parts = spec.split(":")
        host = parts[1] if len(parts) > 1 and parts[1] else "127.0.0.1"
        port = int(parts[2]) if len(parts) > 2 else 8125
        return MultiStatsClient([ExpvarStatsClient(), StatsdStatsClient(host=host, port=port)])
    raise ValueError(f"unknown stats backend: {spec!r}")
