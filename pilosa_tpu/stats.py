"""Stats clients: counters/gauges/histograms with tag support.

Reference analog: stats.go — the StatsClient interface
(Count/Gauge/Histogram/Set/Timing/WithTags, stats.go:33-54), the
expvar-backed client (stats.go:70-130), MultiStatsClient (stats.go:133-185)
and the datadog statsd sink (datadog/datadog.go).  Here the statsd sink
speaks the plain UDP statsd wire format (datadog-compatible with |#tags).
"""

from __future__ import annotations

import random
import socket
import threading

from pilosa_tpu.analysis import lockcheck
from collections import defaultdict
from typing import Iterable

# Per-series sample cap for the expvar histogram/timing reservoirs: a
# long-lived server records totals/min/max exactly and keeps a uniform
# Algorithm-R sample of this size for the percentiles, instead of
# appending every observation forever.
RESERVOIR_CAP = 4096


class NopStatsClient:
    def with_tags(self, *tags: str) -> "NopStatsClient":
        return self

    def count(self, name: str, value: int = 1) -> None:
        pass

    def gauge(self, name: str, value: float) -> None:
        pass

    def histogram(self, name: str, value: float) -> None:
        pass

    def set(self, name: str, value: str) -> None:
        pass

    def timing(self, name: str, value: float) -> None:
        pass


# Shared null-object instance: data-model objects coerce stats=None to
# this so emission sites need no truthiness guards.
NOP_STATS = NopStatsClient()


class ExpvarStatsClient:
    """In-process stats exposed at /debug/vars (stats.go:70-130)."""

    def __init__(self, tags: tuple[str, ...] = ()):
        self._lock = lockcheck.named_lock("stats._lock")
        self._counters: dict[str, int] = defaultdict(int)
        self._gauges: dict[str, float] = {}
        self._sets: dict[str, str] = {}
        # Bounded reservoirs (RESERVOIR_CAP samples) + exact running
        # metadata per series: [count, min, max, sum] for histograms,
        # [count, sum] for timings.
        self._histograms: dict[str, list[float]] = defaultdict(list)
        self._hist_meta: dict[str, list[float]] = {}
        self._timings: dict[str, list[float]] = defaultdict(list)
        self._timing_meta: dict[str, list[float]] = {}
        self._rng = random.Random(0)
        self._tags = tags
        self._children: dict[tuple[str, ...], ExpvarStatsClient] = {}

    def _key(self, name: str) -> str:
        return f"{name}[{','.join(self._tags)}]" if self._tags else name

    def with_tags(self, *tags: str) -> "ExpvarStatsClient":
        key = tuple(sorted(set(self._tags) | set(tags)))
        # Locked lookup-or-create: every handler thread reaches here
        # (tenant/class tags), and the unlocked get-then-store lost a
        # child — or tears _children outright without the GIL.
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = ExpvarStatsClient(tags=key)
                # share the top-level maps so /debug/vars sees everything
                child._lock = self._lock
                child._counters = self._counters
                child._gauges = self._gauges
                child._sets = self._sets
                child._histograms = self._histograms
                child._hist_meta = self._hist_meta
                child._timings = self._timings
                child._timing_meta = self._timing_meta
                child._rng = self._rng
                self._children[key] = child
            return child

    def count(self, name: str, value: int = 1) -> None:
        with self._lock:
            self._counters[self._key(name)] += value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[self._key(name)] = value

    def _reservoir_add(self, samples: list[float], n_total: int, value: float) -> None:
        """Algorithm R: every observation has cap/n odds of residing in
        the sample once the reservoir is full — bounded memory, uniform
        percentiles."""
        if len(samples) < RESERVOIR_CAP:
            samples.append(value)
            return
        j = self._rng.randrange(n_total)
        if j < RESERVOIR_CAP:
            samples[j] = value

    def histogram(self, name: str, value: float) -> None:
        with self._lock:
            key = self._key(name)
            meta = self._hist_meta.get(key)
            if meta is None:
                meta = self._hist_meta[key] = [0, value, value, 0.0]
            meta[0] += 1
            meta[1] = min(meta[1], value)
            meta[2] = max(meta[2], value)
            meta[3] += value
            self._reservoir_add(self._histograms[key], meta[0], value)

    def set(self, name: str, value: str) -> None:
        with self._lock:
            self._sets[self._key(name)] = value

    def timing(self, name: str, value: float) -> None:
        with self._lock:
            key = self._key(name)
            meta = self._timing_meta.get(key)
            if meta is None:
                meta = self._timing_meta[key] = [0, 0.0]
            meta[0] += 1
            meta[1] += value
            self._reservoir_add(self._timings[key], meta[0], value)

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = dict(self._counters)
            out.update(self._gauges)
            out.update(self._sets)
            for name, vals in self._histograms.items():
                if vals:
                    # count/min/max are exact totals; the percentiles
                    # (p50/p95/p99 — the dashboard set, so consumers of
                    # e.g. qos.latency_ms.<class> never re-derive them
                    # from raw samples) read the bounded reservoir.
                    n_total, lo, hi = self._hist_meta[name][:3]
                    s = sorted(vals)
                    out[name] = {
                        "count": int(n_total),
                        "min": lo,
                        "max": hi,
                        "p50": s[len(s) // 2],
                        "p95": s[min(len(s) - 1, int(len(s) * 0.95))],
                        "p99": s[min(len(s) - 1, int(len(s) * 0.99))],
                    }
            for name, vals in self._timings.items():
                if vals:
                    n_total, total = self._timing_meta[name]
                    out[name + ".avg_ms"] = total / n_total * 1000
            return out

    def snapshot_typed(self) -> dict:
        """Kind-preserving snapshot for the Prometheus exposition
        (metrics.py): /debug/vars' flat snapshot() merges counters,
        gauges and sets into one dict, which cannot be mapped back to
        Prometheus metric types mechanically — this keeps each family
        separate.  Histogram entries carry the exact running
        count/min/max/sum plus reservoir percentiles; timings carry
        count/sum."""
        with self._lock:
            hists: dict = {}
            for name, vals in self._histograms.items():
                if vals:
                    n_total, lo, hi, total = self._hist_meta[name]
                    s = sorted(vals)
                    hists[name] = {
                        "count": int(n_total),
                        "min": lo,
                        "max": hi,
                        "sum": total,
                        "p50": s[len(s) // 2],
                        "p95": s[min(len(s) - 1, int(len(s) * 0.95))],
                        "p99": s[min(len(s) - 1, int(len(s) * 0.99))],
                    }
            timings = {
                name: {"count": int(meta[0]), "sum": meta[1]}
                for name, meta in self._timing_meta.items()
                if meta[0]
            }
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "sets": dict(self._sets),
                "histograms": hists,
                "timings": timings,
            }


class StatsdStatsClient:
    """UDP statsd sink with datadog-style |#tag lists (datadog/datadog.go)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125, prefix: str = "pilosa.", tags: tuple[str, ...] = ()):
        self.addr = (host, port)
        self.prefix = prefix
        self._tags = tags
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def with_tags(self, *tags: str) -> "StatsdStatsClient":
        c = StatsdStatsClient.__new__(StatsdStatsClient)
        c.addr = self.addr
        c.prefix = self.prefix
        c._tags = tuple(sorted(set(self._tags) | set(tags)))
        c._sock = self._sock
        return c

    def _send(self, payload: str) -> None:
        if self._tags:
            payload += "|#" + ",".join(self._tags)
        try:
            self._sock.sendto(payload.encode(), self.addr)
        except OSError:
            pass

    def count(self, name: str, value: int = 1) -> None:
        self._send(f"{self.prefix}{name}:{value}|c")

    def gauge(self, name: str, value: float) -> None:
        self._send(f"{self.prefix}{name}:{value}|g")

    def histogram(self, name: str, value: float) -> None:
        self._send(f"{self.prefix}{name}:{value}|h")

    def set(self, name: str, value: str) -> None:
        self._send(f"{self.prefix}{name}:{value}|s")

    def timing(self, name: str, value: float) -> None:
        self._send(f"{self.prefix}{name}:{value * 1000:.3f}|ms")


class MultiStatsClient:
    """Fan out to several clients (stats.go:133-185)."""

    def __init__(self, clients: Iterable):
        self.clients = list(clients)

    def with_tags(self, *tags: str) -> "MultiStatsClient":
        return MultiStatsClient([c.with_tags(*tags) for c in self.clients])

    def count(self, name: str, value: int = 1) -> None:
        for c in self.clients:
            c.count(name, value)

    def gauge(self, name: str, value: float) -> None:
        for c in self.clients:
            c.gauge(name, value)

    def histogram(self, name: str, value: float) -> None:
        for c in self.clients:
            c.histogram(name, value)

    def set(self, name: str, value: str) -> None:
        for c in self.clients:
            c.set(name, value)

    def timing(self, name: str, value: float) -> None:
        for c in self.clients:
            c.timing(name, value)

    def snapshot(self) -> dict:
        for c in self.clients:
            if hasattr(c, "snapshot"):
                return c.snapshot()
        return {}

    def snapshot_typed(self) -> dict:
        for c in self.clients:
            if hasattr(c, "snapshot_typed"):
                return c.snapshot_typed()
        return {}


def new_stats_client(spec: str):
    """Build a stats client from a config string: "expvar" (default),
    "statsd[:host[:port]]", or "nop" (cmd/server.go stats wiring analog)."""
    spec = (spec or "expvar").strip()
    if spec in ("nop", "none", ""):
        return NopStatsClient()
    if spec == "expvar":
        return ExpvarStatsClient()
    if spec == "statsd" or spec.startswith("statsd:"):
        parts = spec.split(":")
        host = parts[1] if len(parts) > 1 and parts[1] else "127.0.0.1"
        port = int(parts[2]) if len(parts) > 2 else 8125
        return MultiStatsClient([ExpvarStatsClient(), StatsdStatsClient(host=host, port=port)])
    raise ValueError(f"unknown stats backend: {spec!r}")
