"""ctypes bridge to the C++ host-runtime kernels (native/pilosa_native.cpp).

Auto-builds the shared library with the in-tree Makefile on first use when
a toolchain is present; every entry point has a pure-Python/numpy fallback
so the framework runs identically (slower) without it.  The analog of the
reference's asm-vs-Go split (roaring/assembly_asm.go vs assembly.go) for
the host side of this build.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libpilosa_native.so")

from pilosa_tpu.analysis import lockcheck

_lock = lockcheck.named_lock("native._lock")
_lib: Optional[ctypes.CDLL] = None
_lib_path_loaded: Optional[str] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    # analysis-ok: exception-hygiene: toolchain probe; load() reports the miss and Python lanes take over
    except Exception:
        return False


def loaded_path() -> Optional[str]:
    """Absolute path of the .so actually loaded (None = Python lanes).
    The sanitizer gate asserts this matches the ASAN build it pointed
    PILOSA_TPU_NATIVE_LIB at — a silent fallback would pass the suites
    without sanitizing anything."""
    load()
    return _lib_path_loaded


def load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_path_loaded, _tried
    # Lock-free fast path: both fields are only ever set under _lock and
    # transition once (None -> value), so a stale read at worst takes the
    # locked slow path.  Per-op WAL encodes call this on the hot path.
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("PILOSA_TPU_NO_NATIVE", "").lower() in ("1", "true", "yes"):
            return None
        # PILOSA_TPU_NATIVE_LIB points the bridge at an alternate build
        # of the same ABI — the sanitizer gate runs the differential
        # suites against the ASAN/UBSAN .so this way (native/Makefile
        # `asan`/`ubsan` targets; tests/test_native_sanitized.py).  An
        # explicit path is never auto-built: a missing file is a
        # misconfiguration, not a cue to compile the default flavor.
        lib_path = os.environ.get("PILOSA_TPU_NATIVE_LIB", "")
        if lib_path:
            if not os.path.exists(lib_path):
                return None
        else:
            lib_path = _LIB_PATH
            if not os.path.exists(lib_path) and not _build():
                return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError:
            return None
        _lib_path_loaded = os.path.abspath(lib_path)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.pn_fnv1a64.restype = ctypes.c_uint64
        lib.pn_fnv1a64.argtypes = [u8p, ctypes.c_size_t]
        lib.pn_fnv1a32.restype = ctypes.c_uint32
        lib.pn_fnv1a32.argtypes = [u8p, ctypes.c_size_t]
        lib.pn_popcount_u32.restype = ctypes.c_uint64
        lib.pn_popcount_u32.argtypes = [u32p, ctypes.c_size_t]
        lib.pn_popcount_and_u32.restype = ctypes.c_uint64
        lib.pn_popcount_and_u32.argtypes = [u32p, u32p, ctypes.c_size_t]
        lib.pn_varint_encode.restype = ctypes.c_int64
        lib.pn_varint_encode.argtypes = [u64p, ctypes.c_size_t, u8p, ctypes.c_size_t]
        lib.pn_varint_decode.restype = ctypes.c_int64
        lib.pn_varint_decode.argtypes = [u8p, ctypes.c_size_t, u64p, ctypes.c_size_t]
        lib.pn_oplog_encode.restype = None
        lib.pn_oplog_encode.argtypes = [u8p, u64p, ctypes.c_size_t, u8p]
        lib.pn_op_encode1.restype = None
        lib.pn_op_encode1.argtypes = [ctypes.c_uint8, ctypes.c_uint64, u8p]
        # c_void_p + raw .ctypes.data int: cheapest per-call marshalling on
        # the SetBit hot path (data_as() allocates a pointer object).
        lib.pn_array_insert_u32.restype = ctypes.c_int64
        lib.pn_array_insert_u32.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32]
        lib.pn_array_add_logged.restype = ctypes.c_int64
        lib.pn_array_add_logged.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_int32,
        ]
        lib.pn_gram_counts.restype = ctypes.c_int64
        lib.pn_gram_counts.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.pn_serve_pairs.restype = ctypes.c_int64
        lib.pn_serve_pairs.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.pn_oplog_decode.restype = ctypes.c_int64
        lib.pn_oplog_decode.argtypes = [u8p, ctypes.c_size_t, u8p, u64p]
        lib.pn_parse_csv.restype = ctypes.c_int64
        lib.pn_parse_csv.argtypes = [ctypes.c_char_p, ctypes.c_size_t, u64p, u64p, i64p, ctypes.c_size_t]
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.pn_pql_parse.restype = ctypes.c_int64
        lib.pn_pql_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            i32p, i32p, i32p, i32p, i32p, ctypes.c_int64,
            i32p, i32p, i32p, i64p, i32p, i32p,
            ctypes.c_int64, i64p,
        ]
        lib.pn_snap_new.restype = ctypes.c_int64
        lib.pn_snap_new.argtypes = []
        lib.pn_snap_free.restype = None
        lib.pn_snap_free.argtypes = [ctypes.c_int64]
        lib.pn_snap_set.restype = None
        lib.pn_snap_set.argtypes = [
            ctypes.c_int64, ctypes.c_uint64, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_size_t,
        ]
        lib.pn_snap_del.restype = None
        lib.pn_snap_del.argtypes = [ctypes.c_int64, ctypes.c_uint64]
        lib.pn_snap_image_size.restype = ctypes.c_int64
        lib.pn_snap_image_size.argtypes = [ctypes.c_int64]
        lib.pn_snap_emit.restype = ctypes.c_int64
        lib.pn_snap_emit.argtypes = [ctypes.c_int64, ctypes.c_void_p, ctypes.c_size_t]
        lib.pn_pql_match_pairs.restype = ctypes.c_int64
        lib.pn_pql_match_pairs.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            u8p, i32p, i32p, i64p, i64p, ctypes.c_int64,
            i32p, i32p, i32p, i32p, i32p, i32p,
            ctypes.c_int32,
        ]
        lib.pn_write_batch.restype = ctypes.c_int64
        lib.pn_write_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,        # src
            ctypes.c_char_p, ctypes.c_int64,        # frame
            ctypes.c_char_p, ctypes.c_int64,        # rowkey
            ctypes.c_char_p, ctypes.c_int64,        # colkey
            ctypes.c_uint64, ctypes.c_uint64,       # slice_i, slice_width
            ctypes.c_void_p, ctypes.c_void_p,       # keys_sorted, buf_addrs
            ctypes.c_void_p, ctypes.c_void_p,       # ns, caps
            ctypes.c_int64,                         # n_containers
            ctypes.c_int64, ctypes.c_int32,         # array_max, wal_fd
            ctypes.c_void_p, ctypes.c_void_p,       # types_out, rows_out
            ctypes.c_void_p, ctypes.c_void_p,       # cols_out, changed_out
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),  # cap, applied
        ]
        lib.pn_serve_multi.restype = ctypes.c_int64
        lib.pn_serve_multi.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,        # src
            ctypes.c_char_p, ctypes.c_void_p,       # names, name_offs
            ctypes.c_char_p, ctypes.c_void_p,       # rlabels, rlabel_offs
            ctypes.c_int64, ctypes.c_int64,         # n_states, default_sid
            ctypes.c_void_p, ctypes.c_void_p,       # rs_addrs, ps_addrs
            ctypes.c_void_p, ctypes.c_void_p,       # gram_addrs, n_rows
            ctypes.c_void_p,                        # gram_dims
            ctypes.c_void_p, ctypes.c_int64,        # out, cap
        ]
        lib.pn_pql_match_range.restype = ctypes.c_int64
        lib.pn_pql_match_range.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            i32p, i32p, i64p, i64p, i64p, ctypes.c_int64,
            i32p, i32p, i32p, i32p, i32p, i32p,
            ctypes.c_int32,
        ]
        lib.pn_serve_tree.restype = ctypes.c_int64
        lib.pn_serve_tree.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,        # src
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,  # frame, allow_default
            ctypes.c_char_p, ctypes.c_int64,        # rowkey
            ctypes.c_void_p, ctypes.c_void_p,       # keys_sorted, buf_addrs
            ctypes.c_void_p, ctypes.c_int64,        # ns, n_containers
            ctypes.c_void_p, ctypes.c_int64,        # bkeys, n_bkeys
            ctypes.c_void_p, ctypes.c_int64,        # out, cap
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _u64(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


# ---------------------------------------------------------------------------
# Public API with fallbacks
# ---------------------------------------------------------------------------

# Below this many values/bytes the ctypes call overhead beats the win;
# the single dispatch point for wire.py's packed fields lives HERE.
_VARINT_NATIVE_THRESHOLD = 64


def varint_encode(values) -> bytes:
    """Packed-varint encode uint64/int64 values (protobuf packed payload).

    Negative values are masked to two's-complement uint64, matching
    proto3 int64 varint encoding (e.g. ImportRequest timestamps).
    """
    try:
        arr = np.ascontiguousarray(values, dtype=np.uint64)
    except OverflowError:
        mask = (1 << 64) - 1
        arr = np.array([int(v) & mask for v in values], dtype=np.uint64)
    lib = load() if len(arr) >= _VARINT_NATIVE_THRESHOLD else None
    if lib is not None and len(arr):
        out = np.empty(len(arr) * 10, dtype=np.uint8)
        n = lib.pn_varint_encode(_u64(arr), len(arr), _u8(out), len(out))
        if n >= 0:
            return out[:n].tobytes()
    from pilosa_tpu.wire import encode_varint

    return b"".join(encode_varint(int(v)) for v in arr.tolist())


def varint_decode(data: bytes) -> np.ndarray:
    """Decode concatenated varints into a uint64 array."""
    lib = load() if len(data) >= _VARINT_NATIVE_THRESHOLD else None
    if lib is not None and data:
        buf = np.frombuffer(data, dtype=np.uint8)
        # Exact value count = bytes with the continuation bit clear.
        count = int(np.count_nonzero(buf < 0x80))
        out = np.empty(count, dtype=np.uint64)
        n = lib.pn_varint_decode(_u8(buf), len(buf), _u64(out), len(out))
        if n < 0:
            raise ValueError("invalid varint stream (truncated or overflows uint64)")
        return out if n == count else out[:n].copy()
    from pilosa_tpu.wire import decode_varint

    out_list = []
    i = 0
    while i < len(data):
        v, i = decode_varint(data, i)
        if v > 0xFFFFFFFFFFFFFFFF:
            raise ValueError("invalid varint stream (truncated or overflows uint64)")
        out_list.append(v)
    return np.array(out_list, dtype=np.uint64)


_op1_local = threading.local()
_wb_local = threading.local()


def op_encode1(typ: int, value: int) -> bytes:
    """One 13-byte WAL op record (the single-SetBit hot path)."""
    lib = load()
    if lib is None:
        from pilosa_tpu.roaring import encode_op

        return encode_op(typ, value)
    buf = getattr(_op1_local, "buf", None)
    if buf is None:
        buf = _op1_local.buf = (ctypes.c_uint8 * 13)()
    lib.pn_op_encode1(typ, value, buf)
    return bytes(buf)


def oplog_encode(types: np.ndarray, values: np.ndarray) -> bytes:
    types = np.ascontiguousarray(types, dtype=np.uint8)
    values = np.ascontiguousarray(values, dtype=np.uint64)
    lib = load()
    if lib is not None and len(types):
        out = np.empty(len(types) * 13, dtype=np.uint8)
        lib.pn_oplog_encode(_u8(types), _u64(values), len(types), _u8(out))
        return out.tobytes()
    from pilosa_tpu.roaring import encode_op

    return b"".join(encode_op(int(t), int(v)) for t, v in zip(types.tolist(), values.tolist()))


def oplog_decode(data: bytes) -> tuple[np.ndarray, np.ndarray]:
    """Decode + checksum-verify a WAL tail; raises ValueError on corruption."""
    if len(data) % 13:
        raise ValueError(f"op data out of bounds: len={len(data)}")
    n = len(data) // 13
    lib = load()
    if lib is not None and n:
        buf = np.frombuffer(data, dtype=np.uint8)
        types = np.empty(n, dtype=np.uint8)
        values = np.empty(n, dtype=np.uint64)
        got = lib.pn_oplog_decode(_u8(buf), len(buf), _u8(types), _u64(values))
        if got < 0:
            raise ValueError(f"checksum mismatch at op {-got - 1}")
        return types, values
    from pilosa_tpu.roaring import decode_op

    types_l, values_l = [], []
    for i in range(n):
        t, v = decode_op(data[i * 13 : (i + 1) * 13])
        types_l.append(t)
        values_l.append(v)
    return np.array(types_l, dtype=np.uint8), np.array(values_l, dtype=np.uint64)


def oplog_decode_prefix(data: bytes) -> tuple[np.ndarray, np.ndarray, int]:
    """Decode the longest valid record prefix of a WAL tail.

    Crash-recovery variant of :func:`oplog_decode`: a torn tail — the
    partial or checksum-corrupt record a crash mid-append leaves — stops
    the decode instead of raising.  Returns (types, values, valid_bytes)
    where ``valid_bytes`` is the byte length of the valid prefix (the
    caller truncates the file there).
    """
    n_full = len(data) // 13
    if n_full == 0:
        return np.empty(0, np.uint8), np.empty(0, np.uint64), 0
    trunc = data[: n_full * 13]
    lib = load()
    if lib is not None:
        buf = np.frombuffer(trunc, dtype=np.uint8)
        types = np.empty(n_full, dtype=np.uint8)
        values = np.empty(n_full, dtype=np.uint64)
        got = lib.pn_oplog_decode(_u8(buf), len(buf), _u8(types), _u64(values))
        k = int(-got - 1) if got < 0 else int(got)
        return types[:k], values[:k], k * 13
    from pilosa_tpu.roaring import decode_op

    types_l, values_l = [], []
    k = 0
    for i in range(n_full):
        try:
            t, v = decode_op(trunc[i * 13 : (i + 1) * 13])
        except ValueError:
            break
        types_l.append(t)
        values_l.append(v)
        k = i + 1
    return np.array(types_l, dtype=np.uint8), np.array(values_l, dtype=np.uint64), k * 13


def _ascii_digits(s: str) -> bool:
    """Plain ASCII decimal digits only — matches pn_parse_csv exactly."""
    return s.isascii() and s.isdigit()


def parse_csv(data: bytes) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Parse 'row,col[,timestamp]' lines → (rows, cols, timestamps)."""
    lib = load()
    if lib is not None and data:
        cap = data.count(b"\n") + 2
        rows = np.empty(cap, dtype=np.uint64)
        cols = np.empty(cap, dtype=np.uint64)
        ts = np.empty(cap, dtype=np.int64)
        n = lib.pn_parse_csv(
            data,
            len(data),
            _u64(rows),
            _u64(cols),
            ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            cap,
        )
        if n < 0:
            raise ValueError(f"malformed CSV at line {-n}")
        return rows[:n].copy(), cols[:n].copy(), ts[:n].copy()
    rows_l, cols_l, ts_l = [], [], []
    for lineno, line in enumerate(data.decode().splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        parts = line.split(",")
        # Mirror the native parser exactly (pn_parse_csv): 2 or 3 fields,
        # plain decimal digits only (no sign, no '_' grouping) — acceptance
        # must not depend on whether the .so loaded.
        if len(parts) < 2 or len(parts) > 3:
            raise ValueError(f"malformed CSV at line {lineno}")
        try:
            if not _ascii_digits(parts[0].strip()) or not _ascii_digits(parts[1].strip()):
                raise ValueError("non-digit id")
            row, col = int(parts[0]), int(parts[1])
            if not (0 <= row < 1 << 64) or not (0 <= col < 1 << 64):
                raise ValueError("id out of uint64 range")
            t = 0
            if len(parts) > 2 and parts[2].strip():
                if not _ascii_digits(parts[2].strip()):
                    raise ValueError("non-digit timestamp")
                t = int(parts[2])
            if not (0 <= t < 1 << 63):
                raise ValueError("timestamp out of int64 range")
            rows_l.append(row)
            cols_l.append(col)
            ts_l.append(t)
        except ValueError:
            raise ValueError(f"malformed CSV at line {lineno}")
    return (
        np.array(rows_l, dtype=np.uint64),
        np.array(cols_l, dtype=np.uint64),
        np.array(ts_l, dtype=np.int64),
    )


def pql_parse_flat(src: bytes):
    """Native PQL fast path: parse a query body into flat preorder arrays.

    Returns None when the library is unavailable or the source needs the
    full Python parser (floats, lists, escapes, any syntax error — the
    caller falls back, keeping error messages identical).  On success
    returns (n_calls, cname_s, cname_e, cnchild, cnargs, cargs_off,
    n_args, ak_s, ak_e, atype, aint, av_s, av_e) — all spans are byte
    offsets into ``src``.
    """
    lib = load()
    if lib is None or not src:
        return None
    # Exact upper bounds from two cheap scans: every call carries a '('
    # and every arg an '=' — far tighter than source-length sizing for
    # large request bodies (a 10MB import body stays ~KBs of arrays).
    call_cap = src.count(b"(") + 1
    arg_cap = src.count(b"=") + 1
    i32 = ctypes.POINTER(ctypes.c_int32)
    cname_s = np.empty(call_cap, dtype=np.int32)
    cname_e = np.empty(call_cap, dtype=np.int32)
    cnchild = np.empty(call_cap, dtype=np.int32)
    cnargs = np.empty(call_cap, dtype=np.int32)
    cargs_off = np.empty(call_cap, dtype=np.int32)
    ak_s = np.empty(arg_cap, dtype=np.int32)
    ak_e = np.empty(arg_cap, dtype=np.int32)
    atype = np.empty(arg_cap, dtype=np.int32)
    aint = np.empty(arg_cap, dtype=np.int64)
    av_s = np.empty(arg_cap, dtype=np.int32)
    av_e = np.empty(arg_cap, dtype=np.int32)
    n_args_out = ctypes.c_int64(0)

    def p(a):
        return a.ctypes.data_as(i32)

    n = lib.pn_pql_parse(
        src, len(src),
        p(cname_s), p(cname_e), p(cnchild), p(cnargs), p(cargs_off), call_cap,
        p(ak_s), p(ak_e), p(atype),
        aint.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), p(av_s), p(av_e),
        arg_cap, ctypes.byref(n_args_out),
    )
    if n < 0:
        return None
    return (
        int(n), cname_s, cname_e, cnchild, cnargs, cargs_off,
        int(n_args_out.value), ak_s, ak_e, atype, aint, av_s, av_e,
    )


# Kernel op names by pn_pql_match_pairs op id.
PQL_PAIR_OPS = ("and", "or", "xor", "andnot")

_PAIR_TAB_CAP = 64  # distinct frame names / row labels per request


def pql_match_pairs(src: bytes):
    """Native matcher for an all-Count(<op>(Bitmap,Bitmap)) request body.

    Returns None (fall back to the slower paths) or
    (op_ids u8[N], frame_ids i32[N] (-1 = default frame), key_ids i32[N],
    r1 i64[N], r2 i64[N], frames list[bytes], keys list[bytes]) where
    frames/keys are the interned distinct spans referenced by the ids.
    """
    lib = load()
    if lib is None or not src:
        return None
    # Cheap bail before any scan/allocation: a request not starting with
    # "Count" (e.g. a megabyte SetBit import body) pays nothing here.
    if not src.lstrip()[:5] == b"Count":
        return None
    call_cap = src.count(b"Count") + 1
    op_ids = np.empty(call_cap, dtype=np.uint8)
    frame_ids = np.empty(call_cap, dtype=np.int32)
    key_ids = np.empty(call_cap, dtype=np.int32)
    r1 = np.empty(call_cap, dtype=np.int64)
    r2 = np.empty(call_cap, dtype=np.int64)
    uf_s = np.empty(_PAIR_TAB_CAP, dtype=np.int32)
    uf_e = np.empty(_PAIR_TAB_CAP, dtype=np.int32)
    uk_s = np.empty(_PAIR_TAB_CAP, dtype=np.int32)
    uk_e = np.empty(_PAIR_TAB_CAP, dtype=np.int32)
    n_frames = ctypes.c_int32(0)
    n_keys = ctypes.c_int32(0)
    i32 = ctypes.POINTER(ctypes.c_int32)
    i64 = ctypes.POINTER(ctypes.c_int64)
    n = lib.pn_pql_match_pairs(
        src, len(src),
        _u8(op_ids), frame_ids.ctypes.data_as(i32), key_ids.ctypes.data_as(i32),
        r1.ctypes.data_as(i64), r2.ctypes.data_as(i64), call_cap,
        uf_s.ctypes.data_as(i32), uf_e.ctypes.data_as(i32), ctypes.byref(n_frames),
        uk_s.ctypes.data_as(i32), uk_e.ctypes.data_as(i32), ctypes.byref(n_keys),
        _PAIR_TAB_CAP,
    )
    if n < 0:
        return None
    frames = [src[uf_s[t]:uf_e[t]] for t in range(n_frames.value)]
    keys = [src[uk_s[t]:uk_e[t]] for t in range(n_keys.value)]
    return (
        op_ids[:n], frame_ids[:n], key_ids[:n], r1[:n], r2[:n], frames, keys,
    )


def gram_counts(op_ids, r1, r2, rows_sorted, pos, gram):
    """Answer a matched pair-count batch from the Gram via count
    identities in one native call (the executor's steady-state lane).

    op_ids: u8[N] (PQL_PAIR_OPS order); r1/r2: i64[N] row ids;
    rows_sorted: i64[R] sorted row-id table; pos: i32[R] matrix positions
    aligned with rows_sorted; gram: C-contiguous i64[D, D].
    Returns i64[N] counts, or None when unavailable or some row id is
    not in the table (caller takes the Python path).
    """
    lib = load()
    if lib is None or not len(op_ids):
        return None
    out = np.empty(len(op_ids), dtype=np.int64)
    rc = lib.pn_gram_counts(
        op_ids.ctypes.data, r1.ctypes.data, r2.ctypes.data, len(op_ids),
        rows_sorted.ctypes.data, pos.ctypes.data, len(rows_sorted),
        gram.ctypes.data, gram.shape[0], out.ctypes.data,
    )
    if rc != 0:
        return None
    return out


def serve_pairs(raw, frame_b, allow_default, rowkey_b, rows_sorted, pos, gram):
    """One-call serving lane: parse + validate + Gram-evaluate a whole
    batched pair-count request in a single GIL-released native call
    (the executor's cached-state steady-state loop; server.go:150 +
    executor.go:1209-1244 analog).

    raw: utf-8 request bytes; frame_b/rowkey_b: expected frame name and
    row-key label bytes; allow_default: the frame may be referenced
    implicitly (it IS the index default).  Table args as gram_counts.
    Returns i64[N] counts or None (caller runs the general path).
    """
    lib = load()
    if lib is None:
        return None
    out = np.empty(4096, dtype=np.int64)
    n = lib.pn_serve_pairs(
        raw, len(raw), frame_b, len(frame_b), 1 if allow_default else 0,
        rowkey_b, len(rowkey_b),
        rows_sorted.ctypes.data, pos.ctypes.data, len(rows_sorted),
        gram.ctypes.data, gram.shape[0], out.ctypes.data, len(out),
    )
    if n < 0:
        return None
    return out[:n]


def serve_multi(raw, names_cat, name_offs, rlabels_cat, rlabel_offs,
                default_sid, rs_addrs, ps_addrs, gram_addrs, n_rows, gram_dims):
    """Multi-frame one-call serving lane (``pn_serve_multi``): the
    serve_pairs crossing generalized to K armed frame states, so a
    dashboard batch spanning several frames still parses, validates, and
    Gram-evaluates in ONE GIL-released native call.

    names_cat/rlabels_cat: concatenated frame-name / row-label bytes with
    i64[K+1] offset fences; rs/ps/gram_addrs: u64[K] RAW base addresses
    of each state's glut arrays; n_rows/gram_dims: i64[K] extents;
    default_sid: state index serving an absent ``frame=`` arg (-1 =
    none).  Returns i64[N] counts or None (caller runs the general path).
    """
    lib = load()
    if lib is None:
        return None
    out = np.empty(4096, dtype=np.int64)
    n = lib.pn_serve_multi(
        raw, len(raw),
        names_cat, name_offs.ctypes.data,
        rlabels_cat, rlabel_offs.ctypes.data,
        len(n_rows), default_sid,
        rs_addrs.ctypes.data, ps_addrs.ctypes.data, gram_addrs.ctypes.data,
        n_rows.ctypes.data, gram_dims.ctypes.data,
        out.ctypes.data, len(out),
    )
    if n < 0:
        return None
    return out[:n]


def pql_match_range(src: bytes):
    """Native matcher for an all-Count(Range(...)) request body.

    Returns None (fall back to the slower paths) or
    (frame_ids i32[N] (-1 = default frame), key_ids i32[N], rows i64[N],
    starts i64[N], ends i64[N], frames list[bytes], keys list[bytes])
    where starts/ends are Y*1e8+M*1e6+D*1e4+h*1e2+m packed minutes —
    digit-validated only; the caller's datetime() conversion keeps the
    sequential path's calendar errors.
    """
    lib = load()
    if lib is None or not src:
        return None
    if not src.lstrip()[:5] == b"Count":
        return None
    call_cap = src.count(b"Count") + 1
    frame_ids = np.empty(call_cap, dtype=np.int32)
    key_ids = np.empty(call_cap, dtype=np.int32)
    rows = np.empty(call_cap, dtype=np.int64)
    starts = np.empty(call_cap, dtype=np.int64)
    ends = np.empty(call_cap, dtype=np.int64)
    uf_s = np.empty(_PAIR_TAB_CAP, dtype=np.int32)
    uf_e = np.empty(_PAIR_TAB_CAP, dtype=np.int32)
    uk_s = np.empty(_PAIR_TAB_CAP, dtype=np.int32)
    uk_e = np.empty(_PAIR_TAB_CAP, dtype=np.int32)
    n_frames = ctypes.c_int32(0)
    n_keys = ctypes.c_int32(0)
    i32 = ctypes.POINTER(ctypes.c_int32)
    i64 = ctypes.POINTER(ctypes.c_int64)
    n = lib.pn_pql_match_range(
        src, len(src),
        frame_ids.ctypes.data_as(i32), key_ids.ctypes.data_as(i32),
        rows.ctypes.data_as(i64), starts.ctypes.data_as(i64),
        ends.ctypes.data_as(i64), call_cap,
        uf_s.ctypes.data_as(i32), uf_e.ctypes.data_as(i32), ctypes.byref(n_frames),
        uk_s.ctypes.data_as(i32), uk_e.ctypes.data_as(i32), ctypes.byref(n_keys),
        _PAIR_TAB_CAP,
    )
    if n < 0:
        return None
    frames = [src[uf_s[t]:uf_e[t]] for t in range(n_frames.value)]
    keys = [src[uk_s[t]:uk_e[t]] for t in range(n_keys.value)]
    return frame_ids[:n], key_ids[:n], rows[:n], starts[:n], ends[:n], frames, keys


def serve_tree(raw, frame_b, allow_default, rowkey_b,
               keys_p, addrs_p, ns_p, n_containers, bkeys_p, n_bkeys):
    """Fused nested-tree serving lane (``pn_serve_tree``): parse an
    all-Count(op-tree over Bitmap leaves) body and evaluate it straight
    off the fragment's armed container table, matcher and evaluator
    fused per container block — intermediate row-id arrays never
    materialize.  The caller holds the fragment lock for the whole call
    (the table's buffers must not move mid-read).

    ``keys_p/addrs_p/ns_p/bkeys_p`` are RAW base-address ints of the
    armed table arrays (see fragment._writelane_state); n_bkeys may be 0.
    Returns i64[N] counts or None (caller runs the general path).
    """
    lib = load()
    if lib is None:
        return None
    out = np.empty(4096, dtype=np.int64)
    n = lib.pn_serve_tree(
        raw, len(raw), frame_b, len(frame_b), 1 if allow_default else 0,
        rowkey_b, len(rowkey_b),
        keys_p, addrs_p, ns_p, n_containers, bkeys_p, n_bkeys,
        out.ctypes.data, len(out),
    )
    if n < 0:
        return None
    return out[:n]


def write_batch(src, frame_b, rowkey_b, colkey_b, slice_i, slice_width,
                keys_p, addrs_p, ns_p, caps_p, n_containers,
                wal_fd, array_max):
    """Native write request lane (``pn_write_batch``): parse + container
    insert + WAL append for a canonical all-SetBit/ClearBit request body
    in ONE GIL-released crossing (the write-side twin of serve_pairs).

    ``keys_p/addrs_p/ns_p/caps_p`` are RAW base-address ints of the
    fragment's container-table arrays (sorted keys, slack-buffer
    addresses, element counts — updated IN PLACE on apply — and buffer
    capacities); raw ints because ``.ctypes.data`` costs ~1.4 us per
    access and this is the singleton hot path — the caller caches them
    alongside the table.  ``wal_fd`` is the raw fragment WAL fd (-1 =
    no WAL attached).

    Returns None when the library is unavailable or the body needs the
    full Python path (parse mismatch), else
    ``(types u8[N], rows u64[N], cols u64[N], changed)`` where
    ``changed`` is a bool array when the ops were APPLIED natively (WAL
    written, ns[] updated) or None when the batch was only PARSED
    (structural decline — the caller applies through the Python batch
    path using the parse).  The returned arrays are views into
    thread-local buffers, valid until the same thread's next call.
    Raises OSError when the WAL write failed after mutation (matching
    the Python batch lane's apply-then-log ordering).
    """
    lib = load()
    if lib is None or not src:
        return None
    # Exact bound: every canonical call contains one "Bit(".
    cap = src.count(b"Bit(")
    if cap <= 0:
        return None
    # Thread-local reused out buffers (pointers cached with them): the
    # singleton hot path would otherwise pay four allocations plus four
    # .ctypes.data accesses per request.
    tl = _wb_local
    arrs = getattr(tl, "arrs", None)
    if arrs is None or len(arrs[0]) < cap:
        size = max(64, cap)
        arrs = tl.arrs = (
            np.empty(size, dtype=np.uint8),
            np.empty(size, dtype=np.uint64),
            np.empty(size, dtype=np.uint64),
            np.empty(size, dtype=np.uint8),
        )
        tl.ptrs = tuple(a.ctypes.data for a in arrs)
        tl.applied = ctypes.c_int64(0)
        tl.applied_ref = ctypes.byref(tl.applied)
    types, rows, cols, changed = arrs
    tp, rp, cp, chp = tl.ptrs
    applied = tl.applied
    applied.value = 0
    n = lib.pn_write_batch(
        src, len(src),
        frame_b, len(frame_b),
        rowkey_b, len(rowkey_b),
        colkey_b, len(colkey_b),
        slice_i, slice_width,
        keys_p, addrs_p, ns_p, caps_p,
        n_containers,
        array_max, wal_fd,
        tp, rp, cp, chp, cap, tl.applied_ref,
    )
    if n == -3:
        raise OSError("WAL write failed")
    if n < 0:
        return None
    return (
        types[:n], rows[:n], cols[:n],
        changed[:n].view(bool) if applied.value else None,
    )


def fnv1a64(data: bytes) -> int:
    lib = load()
    if lib is not None:
        buf = np.frombuffer(data, dtype=np.uint8) if data else np.empty(0, dtype=np.uint8)
        return int(lib.pn_fnv1a64(_u8(buf), len(data)))
    from pilosa_tpu.cluster import fnv1a64 as py_fnv

    return py_fnv(data)


def popcount_words(words: np.ndarray) -> int:
    words = np.ascontiguousarray(words, dtype=np.uint32)
    lib = load()
    if lib is not None:
        return int(lib.pn_popcount_u32(words.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), words.size))
    from pilosa_tpu.roaring import _popcount_words

    return _popcount_words(words)
