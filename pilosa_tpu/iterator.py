"""(row, column) iterators over bitmap data.

Reference analog: iterator.go — the ``Iterator`` interface (iterator.go:24-27)
with ``Seek``/``Next``, plus the concrete kinds: ``BufIterator`` (unread
support, iterator.go:30-79), ``LimitIterator`` (iterator.go:82-119),
``SliceIterator`` over materialized pairs (iterator.go:122-172), and
``RoaringIterator`` mapping linear bit positions to (row, col) via
SliceWidth (iterator.go:175-194).

The hot paths here are vectorized (fragment.merge_block and import work on
whole numpy position arrays at once), so these iterators serve the same
role as the reference's: a small composable streaming layer for
host-side consumers (k-way merges, paging, export) where materializing is
wasteful.  ``next()`` returns ``(row, col)`` or ``None`` at exhaustion
instead of Go's ``(row, col, eof)`` triple.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from pilosa_tpu.pilosa import SLICE_WIDTH

Pair = Tuple[int, int]


class SliceIterator:
    """Iterate a materialized (rows, cols) pair of arrays in order
    (iterator.go:122-172)."""

    def __init__(self, rows, cols):
        rows = np.asarray(rows, dtype=np.uint64)
        cols = np.asarray(cols, dtype=np.uint64)
        if rows.shape != cols.shape:
            raise ValueError("rows/cols length mismatch")
        # Keep (row, col) lexicographic order — the merge invariant.
        order = np.lexsort((cols, rows))
        self._rows = rows[order]
        self._cols = cols[order]
        self._i = 0

    def seek(self, row: int, col: int) -> None:
        """Position at the first pair >= (row, col) (iterator.go:137-151)."""
        key = int(row) * SLICE_WIDTH + int(col)
        keys = self._rows * np.uint64(SLICE_WIDTH) + self._cols
        self._i = int(np.searchsorted(keys, np.uint64(key), side="left"))

    def next(self) -> Optional[Pair]:
        if self._i >= len(self._rows):
            return None
        p = (int(self._rows[self._i]), int(self._cols[self._i]))
        # analysis-ok: check-then-act: iterators are per-execution objects, owned by one thread
        self._i += 1
        return p


class RoaringIterator:
    """Iterate a roaring bitmap of linear positions as (row, col) pairs
    (iterator.go:175-194: pos = row*SliceWidth + col)."""

    def __init__(self, bitmap):
        self._positions = bitmap.to_array()
        self._i = 0

    def seek(self, row: int, col: int) -> None:
        key = np.uint64(int(row) * SLICE_WIDTH + int(col))
        self._i = int(np.searchsorted(self._positions, key, side="left"))

    def next(self) -> Optional[Pair]:
        if self._i >= len(self._positions):
            return None
        pos = int(self._positions[self._i])
        # analysis-ok: check-then-act: iterators are per-execution objects, owned by one thread
        self._i += 1
        return pos // SLICE_WIDTH, pos % SLICE_WIDTH


class BufIterator:
    """Wraps an iterator with a one-element pushback buffer
    (iterator.go:30-79) — the k-way merge primitive."""

    def __init__(self, it):
        self._it = it
        self._buf: Optional[Pair] = None

    def seek(self, row: int, col: int) -> None:
        self._buf = None
        self._it.seek(row, col)

    def next(self) -> Optional[Pair]:
        if self._buf is not None:
            p, self._buf = self._buf, None
            return p
        return self._it.next()

    def peek(self) -> Optional[Pair]:
        if self._buf is None:
            self._buf = self._it.next()
        return self._buf

    def unread(self, pair: Pair) -> None:
        if self._buf is not None:
            raise RuntimeError("unread buffer full")
        self._buf = pair


class LimitIterator:
    """Stops after yielding pairs at or past a row limit
    (iterator.go:82-119)."""

    def __init__(self, it, max_row: int):
        self._it = it
        self._max_row = max_row
        self._eof = False

    def seek(self, row: int, col: int) -> None:
        self._eof = False
        self._it.seek(row, col)

    def next(self) -> Optional[Pair]:
        if self._eof:
            return None
        p = self._it.next()
        if p is None:
            self._eof = True
            return None
        if p[0] > self._max_row:
            # Push the boundary pair back (iterator.go:103-108) so a shared
            # underlying iterator (k-way merge composition) doesn't lose it.
            if hasattr(self._it, "unread"):
                self._it.unread(p)
            self._eof = True
            return None
        return p


def merge_iterators(iterators) -> "SliceIterator":
    """K-way merge of (row, col) iterators into one deduplicated stream —
    the shape fragment.go:812-828 builds for MergeBlock, vectorized."""
    rows, cols = [], []
    for it in iterators:
        while True:
            p = it.next()
            if p is None:
                break
            rows.append(p[0])
            cols.append(p[1])
    if not rows:
        return SliceIterator([], [])
    keys = np.unique(
        np.asarray(rows, dtype=np.uint64) * np.uint64(SLICE_WIDTH)
        + np.asarray(cols, dtype=np.uint64)
    )
    return SliceIterator(keys // np.uint64(SLICE_WIDTH), keys % np.uint64(SLICE_WIDTH))
