"""Anti-entropy: periodic convergence of attrs and fragment data.

Reference analog: HolderSyncer (holder.go:364-562) + FragmentSyncer
(fragment.go:1300-1481).  For every index: sync column attrs with every
peer; for every frame: sync row attrs; for every view/owned slice:
compare per-block checksums against replica peers, pull differing blocks,
majority-vote merge (fragment.merge_block), and push set/clear diffs back
to each peer as SetBit/ClearBit PQL.

Peer failures during a sync pass are SKIPPED (a dead replica must not
break anti-entropy for the live pair) but never silently: every skip
counts ``syncer.peer_errors`` (tagged ``node:<host>``) and updates the
``syncer.last_peer_error`` string at /debug/vars, so a steady anti-
entropy stall (bad peer address, auth wall, wedged node) is visible on
a dashboard instead of only as slowly diverging replicas.
"""

from __future__ import annotations


class HolderSyncer:
    def __init__(self, holder, cluster, host: str, client_factory, stats=None):
        from pilosa_tpu.stats import NOP_STATS

        self.holder = holder
        self.cluster = cluster
        self.host = host
        self.client_factory = client_factory
        self.stats = stats if stats is not None else NOP_STATS
        # Process-lifetime totals (tests, embedders without an expvar
        # sink); the tagged per-node counters live in the stats client.
        self.stat_peer_errors = 0
        self.last_peer_error = ""

    def _peers(self):
        return [n for n in self.cluster.nodes if n.host != self.host]

    def _note_peer_error(self, host: str, where: str, e: BaseException) -> None:
        """One skipped peer interaction: count it (node-tagged) and keep
        the last error string visible at /debug/vars."""
        self.stat_peer_errors += 1
        self.last_peer_error = f"{host} {where}: {e}"
        self.stats.with_tags(f"node:{host}").count("syncer.peer_errors")
        self.stats.set("syncer.last_peer_error", self.last_peer_error)

    # -- attrs (holder.go:385-470) ----------------------------------------

    def sync_index_attrs(self, index_name: str) -> None:
        idx = self.holder.index(index_name)
        if idx is None:
            return
        for node in self._peers():
            client = self.client_factory(node.host)
            try:
                missing = client.column_attr_diff(index_name, idx.column_attr_store.blocks())
            except Exception as e:  # noqa: BLE001 — skip the peer, visibly
                self._note_peer_error(node.host, "column-attr diff", e)
                continue
            for id, attrs in missing.items():
                idx.column_attr_store.set_attrs(id, attrs)

    def sync_frame_attrs(self, index_name: str, frame_name: str) -> None:
        frame = self.holder.frame(index_name, frame_name)
        if frame is None:
            return
        for node in self._peers():
            client = self.client_factory(node.host)
            try:
                missing = client.row_attr_diff(index_name, frame_name, frame.row_attr_store.blocks())
            except Exception as e:  # noqa: BLE001 — skip the peer, visibly
                self._note_peer_error(node.host, "row-attr diff", e)
                continue
            for id, attrs in missing.items():
                frame.row_attr_store.set_attrs(id, attrs)

    # -- fragments (fragment.go:1300-1481) ---------------------------------

    def sync_fragment(self, index_name: str, frame_name: str, view_name: str, slice_i: int) -> None:
        frag = self.holder.fragment(index_name, frame_name, view_name, slice_i)
        if frag is None:
            return
        replicas = [
            n for n in self.cluster.fragment_nodes(index_name, slice_i) if n.host != self.host
        ]
        if not replicas:
            return

        local_blocks = dict(frag.blocks())
        peer_blocks: list[tuple[object, dict[int, bytes]]] = []
        for node in replicas:
            client = self.client_factory(node.host)
            try:
                peer_blocks.append(
                    (node, dict(client.fragment_blocks(index_name, frame_name, view_name, slice_i)))
                )
            except Exception as e:  # noqa: BLE001 — skip the peer, visibly
                self._note_peer_error(node.host, "fragment blocks", e)
                continue

        # Blocks differing on any replica (or missing somewhere).
        all_ids = set(local_blocks)
        for _, blocks in peer_blocks:
            all_ids.update(blocks)
        dirty = [
            bid
            for bid in sorted(all_ids)
            if any(blocks.get(bid) != local_blocks.get(bid) for _, blocks in peer_blocks)
        ]

        for bid in dirty:
            pair_sets = [frag.block_data(bid)]
            nodes = []
            for node, _ in peer_blocks:
                client = self.client_factory(node.host)
                try:
                    pair_sets.append(
                        client.block_data(index_name, frame_name, view_name, slice_i, bid)
                    )
                    nodes.append(node)
                except Exception as e:  # noqa: BLE001 — skip the peer, visibly
                    self._note_peer_error(node.host, "block data", e)
                    continue
            diffs = frag.merge_block(bid, pair_sets)
            # Push each peer its converging diff straight at the fragment
            # (view- and label-agnostic; the reference's PQL push
            # fragment.go:1403-1481 re-derives routing on the peer, which
            # breaks for inverse/time views).
            for node, diff in zip(nodes, diffs[1:]):
                (set_rows, set_cols), (clear_rows, clear_cols) = diff
                if not len(set_rows) and not len(clear_rows):
                    continue
                client = self.client_factory(node.host)
                try:
                    client.post_block_diff(
                        index_name,
                        frame_name,
                        view_name,
                        slice_i,
                        (set_rows.tolist(), set_cols.tolist()),
                        (clear_rows.tolist(), clear_cols.tolist()),
                    )
                except Exception as e:  # noqa: BLE001 — skip the peer, visibly
                    self._note_peer_error(node.host, "block-diff push", e)
                    continue

    # -- full pass (holder.go:364-384) --------------------------------------

    def sync_holder(self) -> None:
        from pilosa_tpu.core.view import VIEW_INVERSE

        for index_name in list(self.holder.indexes):
            idx = self.holder.index(index_name)
            if idx is None:
                continue
            self.sync_index_attrs(index_name)
            max_slice = idx.max_slice()
            max_inverse = idx.max_inverse_slice()
            for frame_name in list(idx.frames):
                frame = idx.frame(frame_name)
                if frame is None:
                    continue
                self.sync_frame_attrs(index_name, frame_name)
                for view_name in list(frame.views):
                    # Inverse views live in the row-id slice space; their
                    # slice range and placement use the inverse max.
                    is_inverse = view_name.startswith(VIEW_INVERSE)
                    upper = max_inverse if is_inverse else max_slice
                    for slice_i in range(upper + 1):
                        if not self.cluster.owns_fragment(self.host, index_name, slice_i):
                            continue
                        self.sync_fragment(index_name, frame_name, view_name, slice_i)
