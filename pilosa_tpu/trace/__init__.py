"""Request-scoped distributed tracing: spans, sampling, slow-query log.

No reference analog — the reference's observability stops at aggregate
expvar counters.  The stack already has counters/histograms (stats.py),
profiles (pprof.py), QoS shed/latency metrics, and qcache hit/miss
telemetry, but none of them can answer "where did THIS request's 72 ms
go?" across parse -> admission -> cache -> slice fan-out -> remote hop
-> device dispatch.  Per-op cost varies wildly with container density
and strategy lane (the same PQL shape can hit the Gram lane, the fused
gather kernels, or the Python general lane), so aggregate histograms
cannot localize a regression; this subsystem attributes time to stages
per request.

Design:

- **Span** — one timed stage: name, start offset, duration, a small tag
  dict (strategy lane, slice counts, cache outcome), children.  Spans
  form a tree rooted at the serving door (HTTP handler or the lockstep
  front end).  Child creation is append-only and thread-safe under the
  GIL, so fan-out worker threads attach their spans concurrently.
- **Head sampling** — the sample decision is made ONCE at the door
  (``Tracer.begin``): an inbound ``X-Pilosa-Trace`` header forces the
  trace (the client override and the cross-node hop), otherwise a coin
  flip against ``[trace] sample-rate`` decides.  An unsampled request
  builds NO span objects — every instrumentation site downstream guards
  on ``span is None``, so the off path is a single branch per site
  (the qcache bench asserts sample-rate 0.01 costs <= 5% vs disabled).
- **Slow-query bypass** — requests whose total duration exceeds
  ``[trace] slow-ms`` are recorded in the ring even when the sampler
  said no (a synthesized root-only trace carries the total + the
  request fingerprint), and ADDITIONALLY emit one structured log line
  on the ``pilosa_tpu.slowquery`` logger: query fingerprint, per-stage
  ms breakdown (when the trace was sampled — head sampling cannot
  retroactively reconstruct stages for unsampled requests), and the
  cache/QoS disposition tags.  Force-sample a repro
  (``X-Pilosa-Trace: 1``) to get the full breakdown for a known-slow
  query.
- **Cross-node propagation** — a coordinator's remote hop sends its
  trace id in ``X-Pilosa-Trace``; the peer (forced by the header)
  traces its own execution and returns the serialized span tree in the
  ``X-Pilosa-Trace-Spans`` response header, which the client grafts
  under the coordinator's ``remote`` span — one trace shows both sides
  of the hop.  All offsets are relative to each span's own start, so
  no clock sync is assumed (the same rule as QoS deadline hops).
- **Lockstep determinism** — in the lockstep service the sampling
  decision is made once on rank 0 at ship time and rides the batch
  wire entry as a per-request ``trace`` flag; every rank reads the
  same flag (never its own RNG), so the decision is identical
  everywhere — the same determinism rule as expired-request drops and
  error isolation.  Only rank 0 records spans (ship/execute phases);
  tracing never changes execution, so workers only count the flags.

Finished traces land in a bounded in-memory ring served at
``/debug/traces`` (JSON, newest-first, ``?min-ms=`` filter).  Config:
``[trace] sample-rate / slow-ms / ring`` TOML, ``PILOSA_TPU_TRACE_*``
env, wired through Config into the server, lockstep CLI, and handler.
"""

from __future__ import annotations

import json
import logging
import random
import threading

from pilosa_tpu.analysis import lockcheck
import time
import uuid
from collections import deque
from typing import Any, Optional

# Request header: "1"/"true" = client force-sample override; any other
# value is a propagated trace id from an upstream hop (which also
# forces sampling, so the coordinator's trace always gets its sub-spans).
TRACE_HEADER = "X-Pilosa-Trace"
# Response header: the serialized span tree of a force-traced request,
# grafted by the caller under its remote-hop span.
TRACE_SPANS_HEADER = "X-Pilosa-Trace-Spans"

# Serialized span payloads ride an HTTP header (stdlib servers cap a
# header line at 64 KiB); past this the wire form degrades to the root
# span only rather than breaking the response.
_SPANS_HEADER_MAX = 30000

DEFAULT_RING = 256

_slow_logger = logging.getLogger("pilosa_tpu.slowquery")


class Span:
    """One timed stage of a request.  Finish is idempotent; an
    unfinished span serializes with its duration measured at
    serialization time (a crash/timeout mid-stage still shows where
    the time went)."""

    __slots__ = ("name", "trace_id", "t0", "ms", "tags", "children")

    def __init__(self, name: str, trace_id: str = ""):
        self.name = name
        self.trace_id = trace_id
        self.t0 = time.perf_counter()
        self.ms: Optional[float] = None
        self.tags: dict = {}
        self.children: list = []

    def child(self, name: str) -> "Span":
        sp = Span(name, self.trace_id)
        self.children.append(sp)  # list.append: atomic under the GIL
        return sp

    def finish(self) -> "Span":
        if self.ms is None:
            self.ms = (time.perf_counter() - self.t0) * 1e3
        return self

    def annotate(self, **tags) -> "Span":
        self.tags.update(tags)
        return self

    def graft(self, payload) -> None:
        """Attach a peer's already-serialized span tree (the decoded
        X-Pilosa-Trace-Spans JSON) under this span.  Stored verbatim —
        remote offsets are relative to the REMOTE request's start, so
        no clock translation is needed or attempted."""
        if isinstance(payload, list):
            self.children.extend(p for p in payload if isinstance(p, dict))
        elif isinstance(payload, dict):
            self.children.append(payload)

    def to_json(self, base_t0: Optional[float] = None) -> dict:
        base = self.t0 if base_t0 is None else base_t0
        ms = self.ms
        if ms is None:  # still running at serialization time
            ms = (time.perf_counter() - self.t0) * 1e3
        out = {
            "name": self.name,
            "start_ms": round((self.t0 - base) * 1e3, 3),
            "ms": round(ms, 3),
        }
        if self.tags:
            out["tags"] = dict(self.tags)
        if self.children:
            out["children"] = [
                c if isinstance(c, dict) else c.to_json(base)
                for c in list(self.children)
            ]
        return out

    def stage_breakdown(self) -> dict:
        """{child name: total ms} over direct children (duplicate names
        sum) — the slow-query log's per-stage view."""
        out: dict = {}
        for c in list(self.children):
            if isinstance(c, dict):
                name, ms = c.get("name", "?"), float(c.get("ms", 0.0))
            else:
                name = c.name
                ms = c.ms if c.ms is not None else 0.0
            out[name] = round(out.get(name, 0.0) + ms, 3)
        return out


class Trace:
    """One sampled request: the root span plus door metadata."""

    __slots__ = ("id", "root", "forced", "propagate", "wall_ts")

    def __init__(self, name: str, trace_id: str = "", forced: bool = False,
                 propagate: bool = False):
        self.id = trace_id or uuid.uuid4().hex[:16]
        self.root = Span(name, self.id)
        self.forced = forced
        # An inbound X-Pilosa-Trace header means the caller wants the
        # span tree back in the response header (a hop, or a client
        # that will read /debug/traces anyway — the extra header is
        # harmless there).
        self.propagate = propagate
        self.wall_ts = time.time()

    def to_json(self, slow_ms: float = 0.0) -> dict:
        root = self.root.to_json()
        return {
            "id": self.id,
            "name": self.root.name,
            "ts": round(self.wall_ts, 3),
            "ms": root["ms"],
            "forced": self.forced,
            "slow": bool(slow_ms > 0 and root["ms"] >= slow_ms),
            "spans": root,
        }


def fingerprint(body: bytes, max_snippet: int = 120) -> dict:
    """Stable identity for a (possibly huge) query body: short hash +
    readable snippet.  Used by the slow-query log so dashboards can
    group recurring slow shapes without storing whole requests."""
    import hashlib

    if not body:
        return {"fp": "", "snippet": ""}
    snippet = body[:max_snippet].decode("utf-8", errors="replace")
    return {
        "fp": hashlib.blake2b(body, digest_size=6).hexdigest(),
        "snippet": snippet,
    }


@lockcheck.guarded_class
class Tracer:
    """Sampling gate + bounded trace ring + slow-query log.

    Thread-safe.  Always constructible: with ``sample_rate=0`` and
    ``slow_ms=0`` only force-header requests trace (the production
    default — an operator can still ``X-Pilosa-Trace: 1`` a repro
    without a restart)."""

    _guarded_by_ = {
        "stat_sampled": "trace._mu",
        "stat_slow": "trace._mu",
        "_ring": "trace._mu",
    }

    def __init__(
        self,
        sample_rate: float = 0.0,
        slow_ms: float = 0.0,
        ring: int = DEFAULT_RING,
        stats=None,
        rng: Optional[random.Random] = None,
        costs=None,
    ):
        from pilosa_tpu.stats import NOP_STATS

        self.sample_rate = max(0.0, min(1.0, float(sample_rate)))
        self.slow_ms = max(0.0, float(slow_ms))
        self.stats = stats if stats is not None else NOP_STATS
        # Per-fingerprint cost ledger (costs.CostLedger): every recorded
        # trace folds into EWMA cost/bandwidth estimates keyed by
        # (index, frame, fingerprint, lane).  None = ledger disabled.
        self.costs = costs
        self._rng = rng if rng is not None else random.Random()
        self._mu = lockcheck.named_lock("trace._mu")
        self._ring: "deque[dict]" = deque(maxlen=max(1, int(ring)))
        self.stat_sampled = 0
        self.stat_slow = 0

    # -- the door ---------------------------------------------------------

    def decide(self, force: bool = False) -> bool:
        """The head-sampling coin flip (exposed separately for the
        lockstep service, which decides once on rank 0 at ship time)."""
        if force:
            return True
        return self.sample_rate > 0.0 and self._rng.random() < self.sample_rate

    def begin(self, headers=None, name: str = "request") -> Optional[Trace]:
        """The per-request entry: an inbound ``X-Pilosa-Trace`` header
        forces the trace (and carries the upstream trace id unless it is
        a bare "1"-style override); otherwise the sampler decides.
        Returns None for the (common) unsampled request — callers pass
        ``trace.root`` downstream only when a trace exists, so every
        downstream site stays a single ``span is None`` branch."""
        raw = (headers or {}).get(_TRACE_HEADER_L)
        if raw is None:
            if not (self.sample_rate > 0.0 and self._rng.random() < self.sample_rate):
                return None
            trace = Trace(name)
        else:
            tid = "" if raw.strip().lower() in ("1", "true", "yes") else raw.strip()
            trace = Trace(name, trace_id=tid, forced=True, propagate=True)
        with self._mu:
            self.stat_sampled += 1
        self.stats.count("trace.sampled")
        return trace

    # -- completion -------------------------------------------------------

    def finish_request(
        self,
        trace: Optional[Trace],
        *,
        name: str,
        dt_ms: float,
        body: bytes = b"",
        status: int = 0,
        tags: Optional[dict] = None,
    ) -> Optional[dict]:
        """Close out one request: record a sampled trace in the ring;
        detect slowness for EVERY request (sampled or not — the slow
        path bypasses sampling) and emit the slow-query log line; return
        extra response headers (the serialized span tree) when the
        caller asked for propagation.  The unsampled fast path is one
        comparison."""
        slow = self.slow_ms > 0.0 and dt_ms >= self.slow_ms
        if trace is None and not slow:
            return None
        if trace is None:
            # Unsampled but slow: synthesize a root-only trace so the
            # ring and the log still carry the event (head sampling
            # cannot reconstruct stages after the fact).
            trace = Trace(name)
            trace.root.ms = dt_ms
            trace.root.tags["unsampled"] = True
        root = trace.root
        root.finish()
        if status:
            root.tags["status"] = status
        if tags:
            root.tags.update(tags)
        self.record(trace)
        if self.costs is not None:
            self.costs.fold(trace, dt_ms, body)
        if slow:
            self._log_slow(trace, dt_ms, body)
        if trace.propagate:
            payload = json.dumps([root.to_json()], separators=(",", ":"))
            if len(payload) > _SPANS_HEADER_MAX:
                # Header-size degradation: keep the root timing, drop
                # the tree rather than breaking the HTTP response.
                slim = root.to_json()
                slim.pop("children", None)
                slim["truncated"] = True
                payload = json.dumps([slim], separators=(",", ":"))
            return {TRACE_SPANS_HEADER: payload}
        return None

    def record(self, trace: Trace) -> None:
        with self._mu:
            self._ring.appendleft(trace.to_json(self.slow_ms))

    def _log_slow(self, trace: Trace, dt_ms: float, body: bytes) -> None:
        with self._mu:
            self.stat_slow += 1
        self.stats.count("trace.slow")
        rec = {
            "trace_id": trace.id,
            "name": trace.root.name,
            "ms": round(dt_ms, 3),
            **fingerprint(body),
            "stages": trace.root.stage_breakdown(),
            # Cache/QoS disposition tags land on the root span
            # (qcache=hit/miss/bypass/ineligible, qos=shed/expired,
            # lane=...) — surfaced flat so the log line is greppable.
            "tags": {k: v for k, v in trace.root.tags.items()},
        }
        _slow_logger.warning("slow-query %s", json.dumps(rec, separators=(",", ":")))

    # -- /debug/traces ----------------------------------------------------

    def traces_json(self, min_ms: float = 0.0, limit: int = 64) -> list[dict]:
        """Newest-first finished traces, optionally filtered by total
        duration (the /debug/traces payload)."""
        with self._mu:
            snap = list(self._ring)
        if min_ms > 0:
            snap = [t for t in snap if t["ms"] >= min_ms]
        return snap[: max(0, int(limit))]

    def __len__(self) -> int:
        return len(self._ring)


_TRACE_HEADER_L = TRACE_HEADER.lower()


def from_config(cfg, stats=None, costs=None) -> Tracer:
    """Build the server's tracer from Config ([trace] TOML +
    PILOSA_TPU_TRACE_* env, resolved by Config itself).  Always returns
    a Tracer: with the all-zero defaults only force-header requests
    trace, which costs one header lookup per request."""
    return Tracer(
        sample_rate=getattr(cfg, "trace_sample_rate", 0.0),
        slow_ms=getattr(cfg, "trace_slow_ms", 0.0),
        ring=getattr(cfg, "trace_ring", DEFAULT_RING),
        stats=stats,
        costs=costs,
    )


def from_env(stats=None, costs=None) -> Optional[Tracer]:
    """Env-only construction for direct embedders (the lockstep service
    when no ctor args are given); None when tracing is fully off so the
    service skips even the per-request header lookup."""
    import os

    rate = float(os.environ.get("PILOSA_TPU_TRACE_SAMPLE_RATE", "0") or 0)  # analysis-ok: env-knob-outside-config: from_env is the documented opt-in for direct embedders; the server wires [trace] config
    slow = float(os.environ.get("PILOSA_TPU_TRACE_SLOW_MS", "0") or 0)  # analysis-ok: env-knob-outside-config: from_env is the documented opt-in for direct embedders; the server wires [trace] config
    ring = int(os.environ.get("PILOSA_TPU_TRACE_RING", str(DEFAULT_RING)))  # analysis-ok: env-knob-outside-config: from_env is the documented opt-in for direct embedders; the server wires [trace] config
    if rate <= 0 and slow <= 0:
        return None
    return Tracer(sample_rate=rate, slow_ms=slow, ring=ring, stats=stats,
                  costs=costs)
