"""SWIM-style gossip membership + broadcast transport.

Reference analog: gossip/gossip.go — ``GossipNodeSet`` wraps
hashicorp/memberlist and implements three interfaces at once: NodeSet
(membership, gossip.go:47-54), Broadcaster (SendSync = direct TCP to every
member gossip.go:124-149, SendAsync = TransmitLimitedQueue gossip
gossip.go:152-164), and memberlist.Delegate (NotifyMsg → BroadcastHandler,
LocalState/MergeRemoteState → StatusHandler, gossip.go:166-222).

This build implements the same contract natively instead of embedding a
library, with the SWIM mechanics memberlist is built on:

- **Failure detection**: periodic UDP probe of a random member; a missed
  ack marks it SUSPECT, a suspicion timeout marks it DEAD.  A suspected
  node that hears its own suspicion refutes it by re-broadcasting itself
  ALIVE with a higher incarnation number.
- **Dissemination**: membership updates and user broadcasts piggyback on
  probe/ack packets, each retransmitted ``retransmit_mult * log2(n+1)``
  times (memberlist's TransmitLimitedQueue discipline).
- **Anti-entropy**: periodic TCP push/pull exchanges the full member list
  plus the application status blob (LocalState/MergeRemoteState — the
  server's schema/maxslice sync hook, server.go:310-391).
- **Join**: TCP push/pull against the seed host (gossip.go:70-76).

Everything is plain sockets + threads; payloads use the same typed
broadcast envelope as the HTTP transport (broadcast.py).
"""

from __future__ import annotations

import json
import math
import random
import socket
import socketserver
import struct
import threading

from pilosa_tpu.analysis import lockcheck
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

# Member states (cluster.go:33-36 NodeState UP/DOWN + SWIM's suspect).
STATE_ALIVE = "alive"
STATE_SUSPECT = "suspect"
STATE_DEAD = "dead"

# UDP frame types.
_PING = 1
_ACK = 2
# TCP frame types.
_USER_MSG = 3
_PUSH_PULL = 4

# Piggyback item kinds.
_PB_MEMBER = 0
_PB_USER = 1

_MAX_UDP = 1350  # stay under typical MTU like memberlist does


@dataclass
class Member:
    name: str  # the node's API host:port — what NodeSet.Nodes() reports
    addr: str  # gossip bind host:port (UDP+TCP)
    incarnation: int = 0
    state: str = STATE_ALIVE
    state_change: float = field(default_factory=time.monotonic)

    def to_wire(self) -> dict:
        return {
            "name": self.name,
            "addr": self.addr,
            "inc": self.incarnation,
            "state": self.state,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "Member":
        return cls(
            name=d["name"], addr=d["addr"], incarnation=d.get("inc", 0),
            state=d.get("state", STATE_ALIVE),
        )


class _LimitedBroadcast:
    """One queued item with a remaining-transmit budget
    (memberlist TransmitLimitedQueue element)."""

    __slots__ = ("payload", "kind", "remaining")

    def __init__(self, kind: int, payload: bytes, remaining: int):
        self.kind = kind
        self.payload = payload
        self.remaining = remaining


def _pack_piggyback(items: list[tuple[int, bytes]]) -> bytes:
    out = [struct.pack("<H", len(items))]
    for kind, body in items:
        out.append(struct.pack("<BI", kind, len(body)))
        out.append(body)
    return b"".join(out)


def _unpack_piggyback(buf: bytes) -> list[tuple[int, bytes]]:
    if len(buf) < 2:
        return []
    (n,) = struct.unpack_from("<H", buf, 0)
    off = 2
    items = []
    for _ in range(n):
        kind, ln = struct.unpack_from("<BI", buf, off)
        off += 5
        items.append((kind, buf[off : off + ln]))
        off += ln
    return items


def _split_addr(addr: str) -> tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host or "127.0.0.1", int(port)


class GossipNodeSet:
    """NodeSet + Broadcaster + failure detector (gossip/gossip.go analog).

    Lifecycle: ``start(handler)`` registers the broadcast handler
    (BroadcastReceiver.Start, gossip.go:57-60), ``open()`` binds sockets,
    joins the seed, and starts the probe / push-pull loops
    (gossip.go:63-86).  For server integration ``start`` may be called
    with the handler and ``open`` afterwards, mirroring the reference's
    ordering requirement.
    """

    def __init__(
        self,
        name: str,
        bind: str = "127.0.0.1:0",
        seed: str = "",
        status_handler=None,
        probe_interval: float = 0.25,
        probe_timeout: float = 0.5,
        suspect_timeout: float = 1.5,
        push_pull_interval: float = 2.0,
        retransmit_mult: int = 3,
        stats=None,
    ):
        from pilosa_tpu.stats import NOP_STATS

        self.name = name
        self.bind = bind
        self.seed = seed
        self.status_handler = status_handler
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.suspect_timeout = suspect_timeout
        self.push_pull_interval = push_pull_interval
        self.retransmit_mult = retransmit_mult
        self.stats = stats if stats is not None else NOP_STATS
        # Process-lifetime total of swallowed errors (tests, embedders
        # without an expvar sink); tagged counters live in the client.
        self.stat_swallowed = 0

        self.handler: Optional[Callable[[bytes], None]] = None
        self._lock = lockcheck.named_rlock("gossip._lock")
        self._members: dict[str, Member] = {}
        self._incarnation = 0
        self._queue: list[_LimitedBroadcast] = []
        self._acks: dict[int, threading.Event] = {}
        self._seq = 0
        self._udp: Optional[socket.socket] = None
        self._tcp: Optional[socketserver.ThreadingTCPServer] = None
        self._closing = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- BroadcastReceiver ------------------------------------------------

    def start(self, handler: Callable[[bytes], None]) -> None:
        self.handler = handler

    # -- lifecycle --------------------------------------------------------

    def open(self) -> None:
        if self.handler is None:
            raise RuntimeError(
                "opening GossipNodeSet: call start(handler) before open()"
            )  # gossip.go:64-66
        host, cfg_port = _split_addr(self.bind)

        nodeset = self

        class _TCPHandler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    hdr = self.rfile.read(5)
                    if len(hdr) < 5:
                        return
                    typ, ln = struct.unpack("<BI", hdr)
                    body = self.rfile.read(ln)
                    resp = nodeset._handle_tcp(typ, body)
                    if resp is not None:
                        self.wfile.write(struct.pack("<BI", _PUSH_PULL, len(resp)) + resp)
                except Exception:
                    # A malformed or torn inbound frame must not kill the
                    # accept loop, but it is never silent.
                    nodeset._note_swallowed("tcp_handler")

        # Gossip needs the SAME port on UDP and TCP (memberlist does too).
        # With an ephemeral bind (":0") the kernel-chosen UDP port may be
        # held by another process on TCP — rebind the pair until both work.
        socketserver.ThreadingTCPServer.allow_reuse_address = True
        last_err: Optional[OSError] = None
        for _ in range(16):
            self._udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._udp.bind((host, cfg_port))
            port = self._udp.getsockname()[1]
            try:
                self._tcp = socketserver.ThreadingTCPServer((host, port), _TCPHandler)
                last_err = None
                break
            except OSError as e:
                last_err = e
                self._udp.close()
                if cfg_port != 0:
                    break  # explicit port: caller asked for exactly this one
        if last_err is not None:
            raise last_err
        self.addr = f"{host}:{port}"
        self.bind = self.addr

        with self._lock:
            self._members[self.name] = Member(
                name=self.name, addr=self.addr, incarnation=self._incarnation
            )

        for target in (self._udp_loop, self._tcp.serve_forever, self._probe_loop, self._push_pull_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

        if self.seed and self.seed != self.addr:
            # Join: full state exchange with the seed (gossip.go:70-76).
            # Briefly retried — a seed that is itself just starting may
            # refuse the first connection (memberlist retries joins too).
            last: Optional[OSError] = None
            for attempt in range(3):
                try:
                    self._push_pull(self.seed)
                    last = None
                    break
                except OSError as e:
                    last = e
                    if attempt < 2:
                        time.sleep(0.2)
            if last is not None:
                raise ConnectionError(f"gossip join to seed {self.seed}: {last}") from last

    def close(self) -> None:
        self._closing.set()
        if self._tcp is not None:
            self._tcp.shutdown()
            self._tcp.server_close()
            self._tcp = None
        if self._udp is not None:
            self._udp.close()
            self._udp = None

    # -- NodeSet ----------------------------------------------------------

    def nodes(self) -> list[str]:
        """Live member names (gossip.go:47-54 — DEAD members drop out)."""
        with self._lock:
            return sorted(
                m.name for m in self._members.values() if m.state != STATE_DEAD
            )

    def member_states(self) -> dict[str, str]:
        """name → alive/suspect/dead, for /status reporting (cluster.go:33-36)."""
        with self._lock:
            return {m.name: m.state for m in self._members.values()}

    # -- Broadcaster ------------------------------------------------------

    def send_sync(self, msg: bytes) -> None:
        """Direct TCP to every live member; any failure raises
        (gossip.go:124-149)."""
        with self._lock:
            targets = [
                m for m in self._members.values()
                if m.name != self.name and m.state != STATE_DEAD
            ]
        errs: list[Exception] = []
        threads = []

        def _send(member: Member):
            try:
                self._tcp_send(member.addr, _USER_MSG, msg)
            except Exception as e:  # collected, first one re-raised
                errs.append(e)

        for m in targets:
            t = threading.Thread(target=_send, args=(m,))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=10.0)
        if errs:
            raise errs[0]

    def send_async(self, msg: bytes) -> None:
        """Queue for piggybacked gossip delivery (gossip.go:152-164).

        Messages too large for a UDP probe's piggyback budget would sit in
        the queue forever; they take the TCP direct path instead (errors
        ignored — async delivery is best-effort).
        """
        if 5 + len(msg) > _MAX_UDP - 200:
            threading.Thread(target=self._quiet_sync, args=(msg,), daemon=True).start()
            return
        self._queue_broadcast(_PB_USER, msg)

    def _note_swallowed(self, where: str) -> None:
        """One intentionally-swallowed error on a best-effort path:
        visible at /debug/vars instead of vanishing."""
        self.stat_swallowed += 1
        self.stats.count(f"gossip.swallowed.{where}")

    def _quiet_sync(self, msg: bytes) -> None:
        try:
            self.send_sync(msg)
        except Exception:
            self._note_swallowed("async_send")

    # -- internals: queue + piggyback -------------------------------------

    def _retransmit_limit(self) -> int:
        with self._lock:
            n = len(self._members)
        return self.retransmit_mult * max(1, math.ceil(math.log2(n + 1)))

    def _queue_broadcast(self, kind: int, payload: bytes) -> None:
        with self._lock:
            self._queue.append(_LimitedBroadcast(kind, payload, self._retransmit_limit()))

    def _get_broadcasts(self, limit: int) -> list[tuple[int, bytes]]:
        """Drain up to ``limit`` bytes of queued items, decrementing their
        budgets (TransmitLimitedQueue.GetBroadcasts)."""
        out: list[tuple[int, bytes]] = []
        used = 0
        with self._lock:
            for lb in list(self._queue):
                cost = 5 + len(lb.payload)
                if cost > _MAX_UDP - 200:
                    # Can never fit any packet's budget — drop instead of
                    # rescanning a dead entry forever.
                    self._queue.remove(lb)
                    continue
                if used + cost > limit:
                    continue
                out.append((lb.kind, lb.payload))
                used += cost
                lb.remaining -= 1
                if lb.remaining <= 0:
                    self._queue.remove(lb)
        return out

    def _broadcast_member(self, m: Member) -> None:
        self._queue_broadcast(_PB_MEMBER, json.dumps(m.to_wire()).encode())

    # -- internals: membership table --------------------------------------

    def _merge_member(self, update: Member) -> None:
        """SWIM update rules: higher incarnation wins; alive refutes suspect
        only with a strictly newer incarnation; self-suspicion triggers
        refutation."""
        requeue = False
        with self._lock:
            if update.name == self.name:
                if update.state in (STATE_SUSPECT, STATE_DEAD) and update.incarnation >= self._incarnation:
                    # Refute: bump incarnation, re-announce ALIVE.
                    self._incarnation = update.incarnation + 1
                    me = self._members[self.name]
                    me.incarnation = self._incarnation
                    me.state = STATE_ALIVE
                    self._broadcast_member(me)
                return
            cur = self._members.get(update.name)
            if cur is None:
                self._members[update.name] = Member(
                    name=update.name, addr=update.addr,
                    incarnation=update.incarnation, state=update.state,
                )
                requeue = True
            else:
                newer = update.incarnation > cur.incarnation
                same = update.incarnation == cur.incarnation
                worse = (
                    (cur.state == STATE_ALIVE and update.state in (STATE_SUSPECT, STATE_DEAD))
                    or (cur.state == STATE_SUSPECT and update.state == STATE_DEAD)
                )
                if newer or (same and worse):
                    cur.incarnation = update.incarnation
                    cur.state = update.state
                    cur.addr = update.addr
                    cur.state_change = time.monotonic()
                    requeue = True
        if requeue:
            self._broadcast_member(self._members[update.name])

    def _mark(self, name: str, state: str) -> None:
        with self._lock:
            m = self._members.get(name)
            if m is None or m.state == state:
                return
            m.state = state
            m.state_change = time.monotonic()
        self._broadcast_member(m)

    # -- internals: UDP probe path ----------------------------------------

    def _udp_loop(self) -> None:
        while not self._closing.is_set():
            sock = self._udp
            if sock is None:
                return
            try:
                data, src = sock.recvfrom(65536)
            except OSError:
                # Transient errors (e.g. ICMP port-unreachable surfacing as
                # ConnectionResetError) must not kill failure detection;
                # only exit once close() is underway.
                if self._closing.is_set() or self._udp is None:
                    return
                continue
            try:
                self._handle_udp(data, src)
            except Exception:
                self._note_swallowed("udp_handler")

    def _handle_udp(self, data: bytes, src) -> None:
        if len(data) < 5:
            return
        typ, seq = struct.unpack_from("<BI", data, 0)
        payload = data[5:]
        if typ == _PING:
            nlen = struct.unpack_from("<H", payload, 0)[0]
            piggy = payload[2 + nlen :]
            self._consume_piggyback(piggy)
            ack = struct.pack("<BI", _ACK, seq) + _pack_piggyback(
                self._get_broadcasts(_MAX_UDP - 5)
            )
            try:
                self._udp.sendto(ack, src)
            except OSError:
                pass
        elif typ == _ACK:
            self._consume_piggyback(payload)
            ev = self._acks.pop(seq, None)
            if ev is not None:
                ev.set()

    def _consume_piggyback(self, buf: bytes) -> None:
        for kind, body in _unpack_piggyback(buf):
            if kind == _PB_MEMBER:
                try:
                    self._merge_member(Member.from_wire(json.loads(body)))
                except (ValueError, KeyError):
                    pass
            elif kind == _PB_USER and self.handler is not None:
                try:
                    self.handler(body)
                except Exception:
                    self._note_swallowed("user_handler")

    def _probe_loop(self) -> None:
        while not self._closing.wait(self.probe_interval):
            with self._lock:
                candidates = [
                    m for m in self._members.values()
                    if m.name != self.name and m.state != STATE_DEAD
                ]
                suspects = [
                    (m.name, m.state_change) for m in self._members.values()
                    if m.state == STATE_SUSPECT
                ]
            now = time.monotonic()
            for name, since in suspects:
                if now - since > self.suspect_timeout:
                    self._mark(name, STATE_DEAD)
            if not candidates:
                continue
            target = random.choice(candidates)
            if not self._probe(target):
                self._mark(target.name, STATE_SUSPECT)

    def _probe(self, member: Member) -> bool:
        with self._lock:
            self._seq += 1
            seq = self._seq
        ev = threading.Event()
        self._acks[seq] = ev
        name_b = self.name.encode()
        pkt = (
            struct.pack("<BI", _PING, seq)
            + struct.pack("<H", len(name_b))
            + name_b
            + _pack_piggyback(
                [(_PB_MEMBER, json.dumps(self._members[self.name].to_wire()).encode())]
                + self._get_broadcasts(_MAX_UDP - 200)
            )
        )
        try:
            self._udp.sendto(pkt, _split_addr(member.addr))
        except OSError:
            self._acks.pop(seq, None)
            return False
        ok = ev.wait(self.probe_timeout)
        self._acks.pop(seq, None)
        return ok

    # -- internals: TCP path ----------------------------------------------

    def _tcp_send(self, addr: str, typ: int, body: bytes) -> bytes:
        with socket.create_connection(_split_addr(addr), timeout=5.0) as s:
            s.sendall(struct.pack("<BI", typ, len(body)) + body)
            if typ != _PUSH_PULL:
                return b""
            hdr = _recv_exact(s, 5)
            rtyp, ln = struct.unpack("<BI", hdr)
            return _recv_exact(s, ln)

    def _handle_tcp(self, typ: int, body: bytes) -> Optional[bytes]:
        if typ == _USER_MSG:
            if self.handler is not None:
                self.handler(body)
            return None
        if typ == _PUSH_PULL:
            self._merge_push_pull(body)
            return self._encode_push_pull()
        return None

    def _encode_push_pull(self) -> bytes:
        with self._lock:
            members = [m.to_wire() for m in self._members.values()]
        status = b""
        if self.status_handler is not None:
            try:
                status = self.status_handler.local_status() or b""
            except Exception:
                self._note_swallowed("local_status")
                status = b""
        head = json.dumps({"members": members}).encode()
        return struct.pack("<I", len(head)) + head + status

    def _merge_push_pull(self, body: bytes) -> None:
        (hlen,) = struct.unpack_from("<I", body, 0)
        head = json.loads(body[4 : 4 + hlen])
        status = body[4 + hlen :]
        for d in head.get("members", []):
            try:
                self._merge_member(Member.from_wire(d))
            except (ValueError, KeyError):
                pass
        if status and self.status_handler is not None:
            try:
                self.status_handler.handle_remote_status(status)
            except Exception:
                self._note_swallowed("remote_status")

    def _push_pull(self, addr: str) -> None:
        resp = self._tcp_send(addr, _PUSH_PULL, self._encode_push_pull())
        if resp:
            self._merge_push_pull(resp)

    def _push_pull_loop(self) -> None:
        while not self._closing.wait(self.push_pull_interval):
            with self._lock:
                candidates = [
                    m for m in self._members.values()
                    if m.name != self.name and m.state == STATE_ALIVE
                ]
            if not candidates:
                continue
            target = random.choice(candidates)
            try:
                self._push_pull(target.addr)
            except OSError:
                pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("gossip peer closed mid-frame")
        buf += chunk
    return buf
